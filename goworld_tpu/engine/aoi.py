"""The AOI-calculator seam: where Spaces meet the TPU.

Reference seam being re-designed (not ported): the reference plugs an
``aoi.AOIManager{Enter,Leave,Moved}`` into each Space
(/root/reference/engine/entity/Space.go:33,105,211,243,259) and receives
synchronous OnEnterAOI/OnLeaveAOI callbacks per mutation
(Entity.go:227-233).  Here the same contract is delivered *batched per tick*:

    1. each Space stages its per-tick arrays (x, z, radius, active);
    2. the game loop calls ``AOIEngine.flush()`` once per tick;
    3. the engine executes one batched step per (backend, capacity) bucket --
       on TPU that is ONE pallas kernel launch for every space of that
       capacity on the chip -- and returns per-space enter/leave event pairs
       in deterministic (observer, observed) order.

Spaces shard over chips with no cross-chip collectives: a bucket's arrays are
sharded over the mesh 'space' axis (see goworld_tpu.parallel.mesh); every
space's [C] rows live wholly on one chip.

Backends:
  * ``cpu`` -- the Python XZ-sweep oracle (the parity oracle);
  * ``cpp`` -- the native C++ sweep (ops/aoi_native, reference role: the
    compiled go-aoi XZList) -- the production host-CPU calculator;
  * ``tpu`` -- persistent device-resident interest state per bucket, pallas
    fused kernel, two-stage device event extraction.

All produce bit-identical events (tests/test_aoi_engine.py,
tests/test_aoi_native.py).
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass

import numpy as np

from .. import faults, telemetry
from ..ops import aoi_cohort as AC
from ..ops import aoi_emit as AE
from ..ops import aoi_fused as AF
from ..ops import aoi_pages as PG
from ..ops import aoi_predicate as P
from ..ops import aoi_stage as AS
from ..ops import dispatch_count as DC
from ..ops.aoi_oracle import CPUAOIOracle
from ..telemetry import trace as _T
from ..telemetry.metrics import Sample
from ..ops import events as EV

# A space handle is stable for the space's lifetime; slots inside a bucket are
# reused after release.

_fused_impl = None  # built lazily: jax must not load in cpu-only processes
_fused_tri_impl = None
_fused_paged_impl = None
_clear_impl = None


def _batched_clear(prev_all, row_slots, row_ents, col_slots, col_words,
                   col_masks):
    """Erase departed entities' rows and columns in ONE device dispatch.

    A migration storm of k entities used to cost 2k sequential ``.at[].set``
    dispatches before the kernel even ran; this scatters all row clears and
    all (pre-combined per (slot, word)) column masks at once.  Callers pad
    the index arrays by repeating a real entry -- both operations are
    idempotent -- so compilation is per padded size, not per k.
    """
    global _clear_impl
    if _clear_impl is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def impl(prev_all, row_slots, row_ents, col_slots, col_words,
                 col_masks):
            prev_all = prev_all.at[row_slots, row_ents, :].set(0)
            cols = prev_all[col_slots, :, col_words] & col_masks[:, None]
            prev_all = prev_all.at[col_slots, :, col_words].set(cols)
            return prev_all

        _clear_impl = impl
    return _clear_impl(prev_all, row_slots, row_ents, col_slots, col_words,
                       col_masks)


_LANES = 128
_MAX_GAPS = 2048    # escaped chunk-index deltas per flush
_MAX_EXC = 32768    # exception triples (tail + multi-bit words) per flush
# triples-path extraction cap ceiling: the [max_triples, 32] bit matrix
# inside extract_triples is the shape driver (~32 MB of int32 at 2^18), so
# growth stops here and larger ticks permanently take the counted
# full-grid fallback (decode_overflow)
_TRI_MAX = 1 << 18


def _device_fault(e: BaseException) -> bool:
    """Classify an exception as a device-side fault the bucket should
    recover from (vs a logic bug that must propagate).  Injected faults are
    explicit; real jax runtime errors are matched by type name (no jaxlib
    import) and by the canonical XLA status prefixes."""
    if isinstance(e, faults.InjectedFault):
        return True
    if type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
        return True
    msg = str(e)
    return "RESOURCE_EXHAUSTED" in msg or "ALLOCATION" in msg.upper()


def _kernelish_fault(e: BaseException) -> bool:
    """Sub-classify a device fault that surfaced at HARVEST time.  Under
    async dispatch a kernel failure only materializes at the blocking
    fetch, where the seam cannot tell it from a transfer fault -- so the
    calculator-demotion decision keys off the exception itself: an
    injected KernelFailure (or a non-OOM XLA runtime error) demotes the
    calc chain one level, a DeviceOOM/RESOURCE_EXHAUSTED only rebuilds."""
    if isinstance(e, faults.KernelFailure):
        return True
    if isinstance(e, faults.InjectedFault):
        return False
    return type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError") \
        and "RESOURCE_EXHAUSTED" not in str(e)


def _packed_predicate(x, z, r, act, block: int = 2048) -> np.ndarray:  # gwlint: allow[host-sync] -- pure host numpy on the durable copies (recovery path), never device values
    """Host recomputation of one slot's packed interest words [C, W] --
    bit-exact with every device backend (all evaluate the same f32
    predicate; ops/aoi_predicate).  Blocked over observer rows so the
    boolean matrix never materializes at O(C^2) bytes (17 GB at the
    row-sharded C=131072)."""
    c = x.shape[0]
    out = np.empty((c, P.words_per_row(c)), np.uint32)
    xx = np.asarray(x, np.float32)
    zz = np.asarray(z, np.float32)
    rr = np.asarray(r, np.float32)
    aa = np.asarray(act, bool)
    for lo in range(0, c, block):
        hi = min(lo + block, c)
        dx = np.abs(xx[None, :] - xx[lo:hi, None])
        dz = np.abs(zz[None, :] - zz[lo:hi, None])
        rad = rr[lo:hi, None]
        m = (dx <= rad) & (dz <= rad)
        m &= aa[lo:hi, None] & aa[None, :]
        idx = np.arange(lo, hi)
        m[idx - lo, idx] = False  # self-interest excluded, like the kernel
        out[lo:hi] = P.pack_rows(m)
    return out


def _split_rows(tri: np.ndarray) -> dict[int, np.ndarray]:
    """(space_row, i, j) triples -> {space_row: (i, j) pairs}."""
    out: dict[int, np.ndarray] = {}
    if len(tri):
        for s in np.unique(tri[:, 0]).tolist():
            out[s] = tri[tri[:, 0] == s][:, 1:]
    return out


def _build_snapshot(capacity: int, x, z, r, act, sub: bool,
                    words: np.ndarray) -> dict:
    """One space's live-migration wire image (docs/robustness.md).

    Positions travel as a delta-staging packet (ops/aoi_stage.pad_packet --
    PR 2's H2D wire format doubles as the migration serialization), rows all
    zero because the importer scatters into its own slot row; the pow2
    padding duplicates the last entry, which an assignment scatter absorbs
    idempotently.  ``words`` is the previous-tick packed interest state --
    the only other durable truth a tier needs to resume bit-exactly.
    Pending events are NOT part of the snapshot: the migration swap and the
    evacuation path carry them explicitly (delivery, not state)."""
    from ..ops import aoi_stage as AS

    # Snapshot export runs between ticks (a migration/evacuation event,
    # not the flush hot path); the inputs are host shadows already, so
    # asarray only normalizes dtype and pad_packet is numpy-in/numpy-out.
    x = np.asarray(x, np.float32)  # gwlint: allow[host-sync] -- host shadow
    z = np.asarray(z, np.float32)  # gwlint: allow[host-sync] -- host shadow
    nz = np.nonzero((x.view(np.uint32) != 0) | (z.view(np.uint32) != 0))[0]
    pkt = None
    if len(nz):
        pkt = tuple(np.asarray(a) for a in AS.pad_packet(  # gwlint: allow[host-sync] -- migration-time packet build
            np.zeros(len(nz), np.int64), nz, x[nz], z[nz]))
    return {"capacity": capacity, "packet": pkt,
            "r": np.array(r, np.float32, copy=True),
            "act": np.array(act, bool, copy=True),
            "sub": bool(sub),
            "words": np.array(words, np.uint32, copy=True)}


def _unpack_positions(snap: dict) -> tuple[np.ndarray, np.ndarray]:
    """Scatter a snapshot's packet back into dense [C] x/z arrays."""
    c = snap["capacity"]
    x = np.zeros(c, np.float32)
    z = np.zeros(c, np.float32)
    if snap["packet"] is not None:
        _rows, cols, xv, zv = snap["packet"]
        x[cols] = xv
        z[cols] = zv
    return x, z


def _demote_emit(bucket, e: BaseException) -> None:
    """``aoi.emit`` seam fault: the faulted tick's events fall back to the
    host decode (pure numpy on arrays the harvest already fetched, so the
    fallback is bit-exact), and the bucket sticks to the host emit path for
    every later tick (docs/robustness.md emit fallback chain;
    ``reset_emit_path`` re-arms)."""
    from ..utils import gwlog

    bucket._emit = "host"
    bucket.stats["emit_path"] = AE.EMIT_LEVEL["host"]
    gwlog.logger("gw.aoi").warning(
        "AOI bucket (cap %d) emit fan-out fault: %s -- demoting to the "
        "host decode emit path", bucket.capacity, e)


def _emit_expand(bucket, chg_vals, ent_vals, gidx, s_n: int):
    """Classified word stream -> sorted (enter, leave) triples through the
    bucket's emit path (docs/perf.md emit paths): C++ bit expansion when
    the bucket runs emit="native", the numpy host expansion otherwise (for
    word streams "vector" IS the host expansion -- the vector/native split
    only diverges on the single-chip triples path).  The native attempt
    sits behind the ``aoi.emit`` fault seam; any failure is handled HERE --
    never propagated to harvest's device-fault recovery -- by demoting the
    bucket and expanding the same stream on host, bit-exactly.
    Harvest-phase numpy on already-fetched arrays throughout (the gwlint
    flush-phase rule walks emit helpers)."""
    if bucket._emit == "native" and len(chg_vals):
        try:
            faults.check("aoi.emit")
            return AE.expand_words_native(chg_vals, ent_vals, gidx,
                                          bucket.capacity)
        except Exception as e:
            if not (_device_fault(e) or isinstance(e, RuntimeError)):
                raise
            _demote_emit(bucket, e)
    return EV.expand_classified_host(chg_vals, ent_vals, gidx,
                                     bucket.capacity, s_n)


def _fused_bucket_step(prev_all, *args):
    """One device program per bucket flush: gather staged slots' previous
    words, run the fused AOI kernel, scatter the new words back, compact the
    diff with the chunk extraction (ops/events.py extract_chunks -- no
    per-element gathers; the NEW words ride the same chunk gather so
    enter/leave classification is free), and wire-encode the result
    (~5 B/dirty chunk + 12 B/exception) so the host fetch is the encoded
    stream, not raw grids.  A single dispatch instead of six (dispatch
    latency is per tick on the production path).

    ``args`` = (new_buf, chg_buf, vals_buf, nv_buf, lane_buf, csel_buf,
    slot_idx, x_all, z_all, r_all, act_all, sub_all, max_chunks, kcap,
    max_gaps, max_exc) where x_all/z_all/r_all/act_all are the bucket's
    persistent DEVICE-RESIDENT [s_max, C] staged inputs (sub_all [s_max]);
    the staged slots' rows are gathered by ``slot_idx`` inside the program,
    so a delta-staged tick never re-ships unchanged inputs (see
    ops/aoi_stage.py and _TPUBucket.flush).  ``chg``/``new`` and the raw
    grids are kept for cap-overflow recovery -- ``prev_all`` is donated, so
    the diff would otherwise be unrecoverable -- and ALL large outputs ride
    DONATED scratch buffers: returning a freshly allocated device array
    costs real per-dispatch time on a tunneled harness (~230 ms/tick
    measured at 8x8192) even when never fetched, while donated in-place
    buffers are free.
    """
    global _fused_impl
    if _fused_impl is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ..ops.aoi_dense import aoi_step_chg

        @functools.partial(
            jax.jit,
            static_argnames=("max_chunks", "kcap", "max_gaps", "max_exc",
                             "platform"),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6))
        def impl(prev_all, new_buf, chg_buf, vals_buf, nv_buf, lane_buf,
                 csel_buf, slot_idx, x_all, z_all, r_all, act_all, sub_all,
                 max_chunks, kcap, max_gaps, max_exc, platform=None):
            prev_rows = prev_all[slot_idx]
            x = x_all[slot_idx]
            z = z_all[slot_idx]
            r = r_all[slot_idx]
            act = act_all[slot_idx]
            sub = sub_all[slot_idx]
            # platform routing (pallas on TPU, fused dense elsewhere) lives
            # in ONE place: ops/aoi_dense.aoi_step_chg.  ``platform`` is the
            # calculator fallback chain's override: a bucket demoted off the
            # pallas path after a kernel failure forces the dense route
            # (bit-identical results; docs/robustness.md)
            new, chg = aoi_step_chg(x, z, r, act, prev_rows,
                                    platform=platform)
            prev_all = prev_all.at[slot_idx].set(new)
            # subscription mask: slots with no event consumers (all-plain
            # spaces -- their interest state lives in the packed words,
            # derived on demand) contribute NOTHING to the change stream,
            # so the fetch/decode cost scales with subscribed slots only.
            # ``new`` above is unmasked: prev_all must stay authoritative.
            chg = jnp.where(sub[:, None, None], chg, jnp.uint32(0))
            vals, nv, lane, csel, ccnt, nd, mcc = EV.extract_chunks(
                chg, max_chunks, kcap, aux=new, lanes=_LANES)
            enc = EV.encode_row_stream(vals, nv, lane, csel, ccnt,
                                       w=_LANES, max_gaps=max_gaps,
                                       max_exc=max_exc)
            (rowb, bitpos, woff, base_row, n_esc, esc_rows,
             exc_gidx, exc_chg, exc_new, exc_n) = enc
            scalars = jnp.stack([nd, mcc, base_row, n_esc, exc_n])
            new_buf = new_buf.at[:].set(new)
            chg_buf = chg_buf.at[:].set(chg)
            vals_buf = vals_buf.at[:].set(vals)
            nv_buf = nv_buf.at[:].set(nv)
            lane_buf = lane_buf.at[:].set(lane)
            csel_buf = csel_buf.at[:].set(csel)
            return (prev_all, new_buf, chg_buf, vals_buf, nv_buf, lane_buf,
                    csel_buf, rowb, bitpos, woff, esc_rows, exc_gidx,
                    exc_chg, exc_new, scalars)

        _fused_impl = impl
    return _fused_impl(prev_all, *args)


def _fused_bucket_step_tri(prev_all, *args):
    """Triples-mode bucket flush (docs/perf.md emit paths): same gather /
    fused kernel / scatter prologue as :func:`_fused_bucket_step`, but the
    diff compacts straight into fixed-capacity (observer, observed, kind)
    triples ON DEVICE (ops/events.py extract_triples) -- harvest then
    fetches the compact triple buffer plus ONE count scalar, and the host
    never unpacks a word again on the steady path.  The raw ``new``/``chg``
    grids still ride donated scratch for the counted-overflow and
    poisoned-scalar recoveries (prev_all is donated, so the diff would
    otherwise be unrecoverable).

    ``args`` = (new_buf, chg_buf, tri_buf, slot_idx, x_all, z_all, r_all,
    act_all, sub_all, max_triples, platform).
    """
    global _fused_tri_impl
    if _fused_tri_impl is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ..ops.aoi_dense import aoi_step_chg

        @functools.partial(
            jax.jit,
            static_argnames=("max_triples", "platform"),
            donate_argnums=(0, 1, 2, 3))
        def impl(prev_all, new_buf, chg_buf, tri_buf, slot_idx, x_all,
                 z_all, r_all, act_all, sub_all, max_triples,
                 platform=None):
            prev_rows = prev_all[slot_idx]
            x = x_all[slot_idx]
            z = z_all[slot_idx]
            r = r_all[slot_idx]
            act = act_all[slot_idx]
            sub = sub_all[slot_idx]
            new, chg = aoi_step_chg(x, z, r, act, prev_rows,
                                    platform=platform)
            prev_all = prev_all.at[slot_idx].set(new)
            chg = jnp.where(sub[:, None, None], chg, jnp.uint32(0))
            tri, count = EV.extract_triples(chg, new, chg.shape[1],
                                            max_triples)
            new_buf = new_buf.at[:].set(new)
            chg_buf = chg_buf.at[:].set(chg)
            tri_buf = tri_buf.at[:].set(tri)
            return (prev_all, new_buf, chg_buf, tri_buf,
                    count.reshape(1))

        _fused_tri_impl = impl
    return _fused_tri_impl(prev_all, *args)


def _fused_bucket_step_paged(prev_all, *args):
    """Paged-mode bucket flush (docs/perf.md paged storage, ROADMAP #2):
    same gather / fused kernel / scatter prologue as
    :func:`_fused_bucket_step`, but the diff compacts into page-granular
    word entries through the on-device allocator (ops/aoi_pages): each
    allocation bin's nonzero change words land on pages drawn from the
    shared free list, so a dense hotspot borrows pages sparse bins never
    needed and NO global per-tick cap exists -- bins the pool cannot
    serve are reported in ``spill_bins`` for the counted spill-to-host
    fallback instead of truncating anything.  Harvest fetches the used
    page prefix, the page table, and one scalar vector.  The raw
    ``new``/``chg`` grids still ride donated scratch for the spill and
    poisoned-scalar recoveries.

    ``args`` = (new_buf, chg_buf, pg_buf, pc_buf, pn_buf, free, slot_idx,
    x_all, z_all, r_all, act_all, sub_all, page_words, bin_words,
    max_spill, platform).
    """
    global _fused_paged_impl
    if _fused_paged_impl is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ..ops.aoi_dense import aoi_step_chg

        @functools.partial(
            jax.jit,
            static_argnames=("page_words", "bin_words", "max_spill",
                             "platform"),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6))
        def impl(prev_all, new_buf, chg_buf, pg_buf, pc_buf, pn_buf,
                 free, slot_idx, x_all, z_all, r_all, act_all, sub_all,
                 page_words, bin_words, max_spill, platform=None):
            prev_rows = prev_all[slot_idx]
            x = x_all[slot_idx]
            z = z_all[slot_idx]
            r = r_all[slot_idx]
            act = act_all[slot_idx]
            sub = sub_all[slot_idx]
            new, chg = aoi_step_chg(x, z, r, act, prev_rows,
                                    platform=platform)
            prev_all = prev_all.at[slot_idx].set(new)
            chg = jnp.where(sub[:, None, None], chg, jnp.uint32(0))
            (pg, pc, pn, page_tab, free_next, spill_bins,
             scalars) = PG.allocate_pages(chg, new, free, page_words,
                                          bin_words, max_spill)
            new_buf = new_buf.at[:].set(new)
            chg_buf = chg_buf.at[:].set(chg)
            pg_buf = pg_buf.at[:].set(pg)
            pc_buf = pc_buf.at[:].set(pc)
            pn_buf = pn_buf.at[:].set(pn)
            return (prev_all, new_buf, chg_buf, pg_buf, pc_buf, pn_buf,
                    page_tab, free_next, spill_bins, scalars)

        _fused_paged_impl = impl
    return _fused_paged_impl(prev_all, *args)


class _CapDecay:
    """Windowed decay of adaptive extraction caps, shared by the TPU
    buckets (single-chip and mesh).  Growth on overflow is the owner's
    job; this tracks window peaks and proposes shrinks on a SHORT doubling
    window -- a one-off mass tick (space fill, restore storm) must not
    pessimize hundreds of later flushes with storm-sized extraction grids.
    ``steady`` turns True once a window check passes with nothing to
    change, i.e. the static compile key is final; benchmarks warm up until
    then."""

    def __init__(self, nd_floor: int):
        self.nd_floor = nd_floor
        self.peak_nd = 0
        self.peak_mcc = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def reset_after_growth(self) -> None:
        """The storm that grew the caps must not anchor the next window's
        peak, or the post-storm shrink waits a full window."""
        self.peak_nd = self.peak_mcc = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def observe(self, nd: int, mcc: int, cur_nd: int,
                cur_k: int) -> tuple[int, int] | None:
        """Track one flush's peaks; at the window boundary return the
        shrunk ``(max_chunks, kcap)`` to adopt, or None."""
        self.peak_nd = max(self.peak_nd, nd)
        self.peak_mcc = max(self.peak_mcc, mcc)
        self.flushes += 1
        if self.flushes < self.refit_at:
            return None
        fit_nd = max(self.nd_floor, -(-self.peak_nd * 3 // 2 // 512) * 512)
        fit_k = min(max(8, 1 << (self.peak_mcc * 2 - 1).bit_length()),
                    _LANES)
        self.peak_nd = self.peak_mcc = 0
        self.flushes = 0
        self.refit_at = min(self.refit_at * 2, 128)
        if fit_nd < cur_nd or fit_k < cur_k:
            self.steady = False  # one more clean window confirms
            return min(cur_nd, fit_nd), min(cur_k, fit_k)
        self.steady = True
        return None


class _TriCapDecay:
    """Windowed decay of the triples-path extraction cap (the exact
    _CapDecay story for ``max_triples``: growth on overflow is the owner's
    job, this proposes post-storm shrinks on a doubling window and reports
    ``steady`` once the static compile key is final)."""

    def __init__(self, floor: int):
        self.floor = floor
        self.peak = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def reset_after_growth(self) -> None:
        self.peak = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def observe(self, count: int, cur: int) -> int | None:
        """Track one flush's triple count; at the window boundary return
        the shrunk cap to adopt, or None."""
        self.peak = max(self.peak, count)
        self.flushes += 1
        if self.flushes < self.refit_at:
            return None
        fit = max(self.floor,
                  1 << (max(self.peak * 3 // 2, 1) - 1).bit_length())
        self.peak = 0
        self.flushes = 0
        self.refit_at = min(self.refit_at * 2, 128)
        if fit < cur:
            self.steady = False  # one more clean window confirms
            return fit
        self.steady = True
        return None


class _PageDecay:
    """Windowed decay of the paged pool size (the exact _TriCapDecay
    story for ``n_pages``: growth on spill is the owner's job -- bounded
    by ops/aoi_pages.pool_ceiling, past which the pool can never spill --
    and this proposes post-storm shrinks on a doubling window, reporting
    ``steady`` once the static compile key is final)."""

    def __init__(self, floor: int):
        self.floor = floor
        self.peak = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def reset_after_growth(self) -> None:
        self.peak = 0
        self.flushes = 0
        self.refit_at = 8
        self.steady = False

    def observe(self, n_used: int, cur: int) -> int | None:
        """Track one flush's used-page peak; at the window boundary
        return the shrunk pool size to adopt, or None."""
        self.peak = max(self.peak, n_used)
        self.flushes += 1
        if self.flushes < self.refit_at:
            return None
        fit = max(self.floor,
                  1 << (max(self.peak * 3 // 2, 1) - 1).bit_length())
        self.peak = 0
        self.flushes = 0
        self.refit_at = min(self.refit_at * 2, 128)
        if fit < cur:
            self.steady = False  # one more clean window confirms
            return fit
        self.steady = True
        return None


def _paged_absorb_chip(bk, chg_dev, new_dev, W: int):  # gwlint: allow[host-sync] -- counted overflow absorber: fetches used pages + spilled bins instead of a chip's full diff grid
    """Absorb one chip's decode overflow through the paged pool
    (docs/perf.md, paged storage): instead of growing the stream caps (a
    recompile) and fetching the chip's FULL diff grid, compact the kept
    change/new grids into pages ON DEVICE (ops/aoi_pages) and fetch only
    the used prefix -- plus any spilled bins host-side, as a counted
    graceful degradation.  Shares the bucket's persistent free list /
    pool-decay state (``_page_free``/``_n_pages``/``_pages``) across
    chips and ticks; the ``aoi.pages`` seam crosses once per absorbed
    chip (oom/fail/partial = whole-grid spill + pool re-arm; poison =
    page-table corruption caught by validation -> whole-grid spill +
    free-list reinit -- the multi-chip pool is transient per-harvest, so
    reinit IS the rebuild).

    Returns ``(chg_vals, ent_vals, gidx)`` with chip-LOCAL flat word
    indices (the caller offsets by its chip base), bit-exact with the
    raw-grid recovery it replaces.
    """
    from ..utils import gwlog
    import jax.numpy as jnp

    nw = int(np.prod(chg_dev.shape))
    bw = PG.bin_words_for(W)
    if bk._pages is None:
        bk._pages = _PageDecay(floor=PG.pool_floor(nw))
    want = max(bk._n_pages, bk._pages.floor)
    if bk._page_free is None or int(bk._page_free.shape[0]) != want:
        bk._n_pages = want
        bk._page_free = jnp.arange(want, dtype=jnp.int32)
    n_pages = bk._n_pages

    def _whole_grid():  # gwlint: allow[host-sync] -- counted whole-grid spill drains on purpose
        # counted spill: the raw-grid fallback the capped path used
        bk.stats["page_spills"] += 1
        chg_h = np.asarray(chg_dev).reshape(-1)
        new_h = np.asarray(new_dev).reshape(-1)
        gidx = np.nonzero(chg_h)[0]
        chg_vals = chg_h[gidx]
        return chg_vals, chg_vals & new_h[gidx], np.asarray(gidx, np.int64)

    try:
        spec = faults.check("aoi.pages")
    except Exception as e:  # noqa: BLE001 -- seam-injected device faults
        if not _device_fault(e):
            raise
        gwlog.logger("gw.aoi").warning(
            "AOI page pool unusable for this chip (%s); spilling its "
            "whole grid to host and re-arming the pool", e)
        bk._page_free = None
        bk._pages.reset_after_growth()
        return _whole_grid()
    if spec is not None and spec.kind == "partial":
        gwlog.logger("gw.aoi").warning(
            "AOI page allocation reported partial for this chip; "
            "spilling its whole grid to host and re-arming the pool")
        bk._page_free = None
        bk._pages.reset_after_growth()
        return _whole_grid()
    _tp = _T.t()
    (pg, pc, pn, tab, free_next, sb, scal) = PG.paged_extract(
        chg_dev.reshape(-1), new_dev.reshape(-1), bk._page_free,
        page_words=PG.PAGE_WORDS, bin_words=bw, max_spill=PG.MAX_SPILL)
    bk._page_free = free_next
    scal_h = np.asarray(scal)
    n_used, n_spill = int(scal_h[0]), int(scal_h[1])
    n_bins = -(-nw // bw)
    tab_h = np.asarray(tab)
    if spec is not None and spec.kind == "poison":
        # seam-injected allocator corruption: trash the fetched table so
        # validation must catch it (docs/robustness.md, aoi.pages)
        tab_h = np.full_like(tab_h, np.iinfo(np.int32).min)
    bad_scal = not (0 <= n_used <= n_pages and 0 <= n_spill <= n_bins)
    if bad_scal or not PG.validate_page_table(
            tab_h, 0 if bad_scal else n_used, n_pages):
        bk.stats["poisoned"] += 1
        gwlog.logger("gw.aoi").warning(
            "AOI page table failed validation during overflow absorb "
            "(n_used=%d, n_pages=%d); spilling the chip's whole grid and "
            "reinitialising the free list", n_used, n_pages)
        bk._page_free = None
        bk._pages.reset_after_growth()
        out = _whole_grid()
        _T.lap("aoi.pages", _tp)
        return out
    pg_h = np.asarray(pg[:max(n_used, 1)])[:n_used]
    pc_h = np.asarray(pc[:max(n_used, 1)])[:n_used]
    pn_h = np.asarray(pn[:max(n_used, 1)])[:n_used]
    gidx, chg_vals, new_vals = PG.decode_pages(pg_h, pc_h, pn_h)
    if n_spill:
        # hotter than the pool: counted spill for the offending bins +
        # pool growth so the NEXT storm tick absorbs fully page-side
        bk.stats["page_spills"] += n_spill
        sgi, sc, sn = PG.spill_stream(
            chg_dev.reshape(-1), new_dev.reshape(-1), np.asarray(sb),
            bw, nw)
        gidx = np.concatenate([np.asarray(gidx, np.int64), sgi])
        chg_vals = np.concatenate([chg_vals, sc])
        new_vals = np.concatenate([new_vals, sn])
        grown = min(PG.pool_ceiling(nw, bw), max(n_pages * 2, 64))
        if grown > n_pages:
            bk._n_pages = grown
            bk._page_free = None
        bk._pages.reset_after_growth()
    else:
        shrink = bk._pages.observe(n_used, n_pages)
        if shrink is not None:
            bk._n_pages = shrink
            bk._page_free = None
    bk.stats["page_occupancy"] = n_used / max(n_pages, 1)
    _T.lap("aoi.pages", _tp)
    return chg_vals, chg_vals & new_vals, np.asarray(gidx, np.int64)


@dataclass(eq=False)  # identity hash: handles live in a WeakSet registry
class SpaceAOIHandle:
    backend: str        # resolved (cpu | cpp | tpu)
    capacity: int
    bucket: "_Bucket"
    slot: int
    released: bool = False
    # the backend as REQUESTED (may be "auto"); growth re-resolves it, so
    # a space that grows past the routing threshold moves to the tpu bucket
    requested: str = ""


class AOIEngine:
    """Per-process registry of AOI state, bucketed by (backend, capacity).

    ``mesh`` (a :class:`goworld_tpu.parallel.SpaceMesh`, or an int device
    count) shards every tpu bucket's spaces over the mesh's 'space' axis --
    the engine-level multi-chip path (see engine/aoi_mesh).  Without it, tpu
    buckets are single-device."""

    _next_telemetry_id = 0

    def __init__(self, default_backend: str = "cpu",
                 oracle_algorithm: str = "sweep", mesh=None,
                 pipeline: bool = False, delta_staging: bool = True,
                 tpu_min_capacity: int = 4096,
                 rowshard_min_capacity: int = 65536,
                 flush_sched: bool = True, emit: str = "auto",
                 paged: bool = False, cross_tick: bool = False,
                 interest_mode: str = "device", fused: bool = False,
                 cohort=False, cohort_ladder=None):
        self.default_backend = default_backend
        # space-stacked cohorts (ROADMAP #2, ops/aoi_cohort, docs/perf.md
        # "Space-stacked cohorts"): "auto"/True stacks small device-eligible
        # spaces into shared ladder-shaped _CohortTPUBucket planes so ONE
        # launch ticks the whole cohort; "solo" forces one exclusive bucket
        # per space -- the O(spaces)-dispatches baseline the engine_multispace
        # bench A/Bs against (and the demotion target of the aoi.cohort
        # seam); False keeps classic (backend, capacity) pooling.  Cohorts
        # are a single-chip tier: a mesh engine keeps its mesh routing.
        if cohort is True:
            cohort = "auto"
        if cohort not in (False, "auto", "solo"):
            raise ValueError(
                f"aoi_cohort must be False|True|'auto'|'solo', got "
                f"{cohort!r}")
        self.cohort = cohort
        self.cohort_ladder = AC.validate_ladder(
            cohort_ladder if cohort_ladder is not None else AC.DEFAULT_LADDER)
        self._cohort_serial = 0
        self.cohort_stats = {"cohort_joins": 0, "cohort_leaves": 0,
                             "cohort_demoted_spaces": 0}
        # fused steady tick (ops/aoi_fused, ROADMAP #3): each device
        # bucket compiles its steady-state tick into ONE jitted program
        # (one enqueue + one D2H fetch); unfused stays the A/B baseline
        # and the per-tick demotion target for any aoi.* seam fault
        self.fused = bool(fused)
        # interest-policy stacks (goworld_tpu/interest/): where attached
        # stacks evaluate -- "device" = the fused jitted step, "host" =
        # the CPU oracle (the bit-exact perf baseline bench_engine_interest
        # A/Bs against).  Validated here, consumed by attach_interest.
        if interest_mode not in ("device", "host"):
            raise ValueError(
                f"interest_mode must be device|host, got {interest_mode!r}")
        self.interest_mode = interest_mode
        # cross-tick pipelining (docs/perf.md): tick T+1's dispatch (pack
        # + H2D + kernel enqueue on the double-buffered device state) runs
        # while tick T harvests -- the device bucket parks each dispatched
        # record one flush and delivers it at the next, buying near-100%
        # device occupancy for ONE TICK of documented event latency.  The
        # deferral is exactly the ``pipeline`` bucket contract, asserted
        # engine-wide: cross_tick composes idempotently with pipeline
        # (either flag defers; both together still defer exactly one
        # tick), and the stream is bit-exact modulo the shift.  The
        # row-sharded tier accepts the flag but stays synchronous (its
        # flush is already a collective barrier -- see aoi_rowshard).
        self.cross_tick = bool(cross_tick)
        # paged ragged event storage (docs/perf.md paged storage): the
        # device buckets compact their change stream into fixed-size pages
        # drawn from a shared on-device free list instead of a global
        # per-tick cap, retiring the decode_overflow failure class for
        # skewed (clustered-crowd) distributions.  Off by default while
        # the capped layouts remain the tuned production path; bench.py's
        # clustered_crowd config A/Bs the two.
        self.paged = bool(paged)
        # event emit fan-out path for the device buckets (docs/perf.md):
        # "auto" = fastest available (native when libgwemit builds, else
        # vector), "host" = the original per-word host decode kept as the
        # bit-exact oracle.  Validated here (fail fast at construction) but
        # RESOLVED lazily at the first tpu bucket -- resolution may shell
        # out to make, which a cpu-only engine must never pay.
        if emit != "auto" and emit not in AE.EMIT_MODES:
            raise ValueError(
                f"aoi_emit must be one of {('auto',) + AE.EMIT_MODES}, "
                f"got {emit!r}")
        self.emit = emit
        self._emit_resolved: str | None = None
        # sparse delta staging of device-resident tick inputs (see
        # _TPUBucket._stage_inputs); False = full-restage baseline, kept
        # for perf A/B in bench.py
        self.delta_staging = delta_staging
        # split-phase flush scheduler (docs/perf.md): True = issue-all-
        # then-harvest across buckets; False = the forced-sequential
        # baseline (each bucket dispatches AND harvests before the next
        # starts), kept for perf A/B and parity tests
        self.flush_sched = flush_sched
        self.oracle_algorithm = oracle_algorithm
        # "auto" routing threshold: spaces below it go to the native host
        # calculator (a tiny space is dispatch-bound on an accelerator;
        # the native sweep finishes in microseconds), larger ones to the
        # tpu bucket where the batched kernel wins
        self.tpu_min_capacity = tpu_min_capacity
        # oversized-single-space threshold: with a mesh, a space at or above
        # this capacity shards its interest ROWS over the chips (each chip
        # owns C/n observers vs all C candidates -- engine/aoi_rowshard)
        # instead of living whole on one chip.  The zipf100k scaling answer.
        self.rowshard_min_capacity = rowshard_min_capacity
        self._rowshard_serial = 0
        if isinstance(mesh, int):
            from ..parallel import SpaceMesh, multichip_devices

            mesh = SpaceMesh(multichip_devices(mesh))
        self.mesh = mesh
        # double-buffered tpu flush: events arrive one tick late, D2H
        # overlaps the host tick (SURVEY §7(d); see _TPUBucket docstring --
        # the mesh bucket implements the same contract per chip)
        self.pipeline = pipeline
        self._buckets: dict[tuple[str, int], _Bucket] = {}
        # live handle registry (weak: a dropped Space must not pin its
        # slot); the chip-loss evacuation path re-points these in place so
        # Spaces survive their bucket dying (docs/robustness.md)
        self._handles: "weakref.WeakSet[SpaceAOIHandle]" = weakref.WeakSet()
        # in-flight live migrations (engine/placement.py _Migration
        # objects); flush() drives their per-flush double-cover compare
        self._migrations: list = []
        self.migration_stats = {"migrations": 0, "evacuations": 0,
                                "migration_rollbacks": 0,
                                "migration_ms": 0.0}
        # unified telemetry: the per-bucket stats/perf dicts surface at
        # /debug/metrics under aoi.* dotted names.  Registered weakly so
        # the registry never keeps a dead engine (and its device state)
        # alive; the label tells concurrent engines apart.
        self._telemetry_id = AOIEngine._next_telemetry_id
        AOIEngine._next_telemetry_id += 1
        telemetry.register_collector(self._telemetry_collect, weak=True)
        if default_backend in ("tpu", "auto"):
            # fail FAST at process boot, not on the first space's first
            # tick: a game configured for tpu whose jax backend is broken
            # (e.g. an explicitly requested device plugin that cannot load)
            # would otherwise come up "healthy" and swallow an error per
            # tick forever.  A *silent* cpu fallback (plugin simply absent)
            # passes this probe but runs the kernel interpreted -- warn
            # loudly; that is right for hermetic tests and wrong for prod.
            #
            # The probe targets the engine's ACTUAL compute platform.  With a
            # mesh, every byte of engine compute runs on the mesh's devices
            # -- probing the default backend there once turned a hermetic CPU
            # dryrun red when an unrelated rolling libtpu upgrade broke a TPU
            # the engine never touches (round-3 MULTICHIP artifact).
            import jax

            if self.mesh is not None:
                dev = next(iter(self.mesh.mesh.devices.flat))
                jax.device_put(np.zeros(8, np.float32),  # gwlint: allow[host-sync] -- one-time boot probe at engine init, not per-tick
                               dev).block_until_ready()
                if self.mesh.platform != "tpu":
                    from ..utils import gwlog

                    gwlog.logger("gw.aoi").warning(
                        "aoi_backend=tpu on a %r mesh -- the kernel will run "
                        "in interpret mode (fine for tests/dryruns, orders "
                        "of magnitude too slow for production)",
                        self.mesh.platform,
                    )
            else:
                import jax.numpy as jnp

                jnp.zeros(8).block_until_ready()  # gwlint: allow[host-sync] -- one-time boot probe at engine init, not per-tick
                if jax.default_backend() != "tpu":
                    # EXACTLY the kernel's interpret condition
                    # (aoi_pallas: backend != "tpu" -> interpret mode), so
                    # any interpreted fallback is loud
                    from ..utils import gwlog

                    gwlog.logger("gw.aoi").warning(
                        "aoi_backend=tpu but jax default backend is %r -- "
                        "the kernel will run in interpret mode (fine for "
                        "tests, orders of magnitude too slow for production)",
                        jax.default_backend(),
                    )

    def create_space(self, capacity: int, backend: str | None = None) -> SpaceAOIHandle:
        requested = backend or self.default_backend
        capacity = P.round_capacity(capacity)
        if self.cohort and self.mesh is None \
                and requested in ("tpu", "auto"):
            # cohort routing (docs/perf.md "Space-stacked cohorts"): a
            # device-eligible space inside the ladder range rounds UP to
            # its pow2 ladder shape -- "auto" stacks it into the shared
            # cohort bucket at that shape (one launch per cohort, not per
            # space), "solo" pins it to an exclusive per-space bucket
            # (the O(spaces) baseline / demotion target).  Spaces past
            # the ladder ceiling keep the classic routing below.
            shape = AC.cohort_shape(capacity, self.cohort_ladder)
            if shape is not None:
                if self.cohort == "solo":
                    h = self._solo_handle(shape)
                else:
                    bucket = self._cohort_bucket(shape)
                    slot = bucket.acquire_slot()
                    h = SpaceAOIHandle("tpu", shape, bucket, slot)
                    self._handles.add(h)
                h.requested = requested
                return h
        backend = requested
        if backend == "auto":
            # capacity routing: tiny spaces are dispatch-bound on an
            # accelerator (the native sweep finishes them in microseconds);
            # large ones belong on the batched kernel
            backend = ("tpu" if capacity >= self.tpu_min_capacity
                       else "cpp")
        rowshard = (backend == "tpu" and self.mesh is not None
                    and capacity >= self.rowshard_min_capacity
                    and capacity % (self.mesh.n_devices * 128) == 0)
        key = (backend, capacity)
        bucket = None if rowshard else self._buckets.get(key)
        if bucket is None:
            if backend == "cpu":
                bucket = _CPUBucket(capacity, self.oracle_algorithm)
            elif backend == "cpp":
                from ..ops import aoi_native

                if aoi_native.available():
                    # "auto" = grid candidate binning when the layout
                    # supports it, sweep otherwise (bit-exact either way);
                    # the production host calculator should always take the
                    # cheaper enumeration
                    bucket = _CPUBucket(capacity, "auto",
                                        oracle_cls=aoi_native.NativeAOIOracle)
                else:
                    # LOUD fallback (results are bit-identical, only slower)
                    from ..utils import gwlog

                    gwlog.logger("gw.aoi").warning(
                        "libgwaoi.so unavailable (no C++ toolchain?); "
                        "aoi_backend=cpp falling back to the python oracle"
                    )
                    bucket = _CPUBucket(capacity, self.oracle_algorithm)
            elif backend == "tpu":
                if rowshard:
                    # oversized single space: shard its interest rows over
                    # the mesh; one EXCLUSIVE bucket per space (at C=131072
                    # the packed state is 2 GB mesh-wide -- released with
                    # the space, never pooled)
                    from .aoi_rowshard import _RowShardTPUBucket

                    bucket = _RowShardTPUBucket(
                        capacity, self.mesh, pipeline=self.pipeline,
                        cross_tick=self.cross_tick,
                        delta_staging=self.delta_staging,
                        emit=self._resolve_emit(), paged=self.paged,
                        fused=self.fused)
                    self._rowshard_serial += 1
                    key = (f"tpu-rowshard-{self._rowshard_serial}", capacity)
                elif self.mesh is not None:
                    from .aoi_mesh import _MeshTPUBucket

                    bucket = _MeshTPUBucket(
                        capacity, self.mesh, pipeline=self.pipeline,
                        cross_tick=self.cross_tick,
                        delta_staging=self.delta_staging,
                        emit=self._resolve_emit(), paged=self.paged,
                        fused=self.fused)
                else:
                    bucket = _TPUBucket(capacity, pipeline=self.pipeline,
                                        cross_tick=self.cross_tick,
                                        delta_staging=self.delta_staging,
                                        emit=self._resolve_emit(),
                                        paged=self.paged,
                                        fused=self.fused)
            else:
                raise ValueError(f"unknown AOI backend {backend!r}")
            self._buckets[key] = bucket
        slot = bucket.acquire_slot()
        h = SpaceAOIHandle(backend, capacity, bucket, slot,
                           requested=requested)
        self._handles.add(h)
        return h

    def _create_handle(self, capacity: int, tier: str) -> SpaceAOIHandle:
        """Acquire a slot on an EXPLICIT bucket tier (``cpu`` | ``cpp`` |
        ``tpu`` | ``mesh`` | ``rowshard``) -- the placement controller's
        entry point: capacity routing is create_space's job, but a
        migration target chosen by scoring must land exactly where the
        controller said.  ``tier="tpu"`` means the single-chip bucket even
        on a mesh engine (keyed ``tpu-single`` so it never collides with
        the mesh bucket at the same capacity)."""
        capacity = P.round_capacity(capacity)
        if tier in ("cpu", "cpp"):
            return self.create_space(capacity, tier)
        if tier == "rowshard":
            if self.mesh is None or capacity % (self.mesh.n_devices * 128):
                raise ValueError(
                    f"capacity {capacity} cannot row-shard on this engine")
            from .aoi_rowshard import _RowShardTPUBucket

            bucket = _RowShardTPUBucket(
                capacity, self.mesh, pipeline=self.pipeline,
                cross_tick=self.cross_tick,
                delta_staging=self.delta_staging, emit=self._resolve_emit(),
                paged=self.paged, fused=self.fused)
            self._rowshard_serial += 1
            self._buckets[(f"tpu-rowshard-{self._rowshard_serial}",
                           capacity)] = bucket
        elif tier == "mesh":
            if self.mesh is None:
                raise ValueError("tier='mesh' requires a mesh engine")
            key = ("tpu", capacity)
            bucket = self._buckets.get(key)
            if bucket is None:
                from .aoi_mesh import _MeshTPUBucket

                bucket = _MeshTPUBucket(
                    capacity, self.mesh, pipeline=self.pipeline,
                    cross_tick=self.cross_tick,
                    delta_staging=self.delta_staging,
                    emit=self._resolve_emit(), paged=self.paged,
                    fused=self.fused)
                self._buckets[key] = bucket
        elif tier == "tpu":
            key = (("tpu-single", capacity) if self.mesh is not None
                   else ("tpu", capacity))
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = _TPUBucket(capacity, pipeline=self.pipeline,
                                    cross_tick=self.cross_tick,
                                    delta_staging=self.delta_staging,
                                    emit=self._resolve_emit(),
                                    paged=self.paged,
                                    fused=self.fused)
                self._buckets[key] = bucket
        else:
            raise ValueError(f"unknown placement tier {tier!r}")
        slot = bucket.acquire_slot()
        h = SpaceAOIHandle("tpu", capacity, bucket, slot, requested="tpu")
        self._handles.add(h)
        return h

    def _resolve_emit(self) -> str:
        """Resolve the requested emit mode once (an explicit/auto "native"
        probes -- and on first use builds -- libgwemit; degrading to
        "vector" when the toolchain is absent must not flap per bucket)."""
        if self._emit_resolved is None:
            self._emit_resolved = AE.resolve_mode(self.emit)
        return self._emit_resolved

    # -- space-stacked cohorts (docs/perf.md "Space-stacked cohorts") -----

    def _cohort_bucket(self, shape: int):
        """Get-or-create the shared cohort bucket at a ladder shape.  One
        bucket per shape: membership churn re-buckets spaces between
        ladder rungs, never mints new shapes, so the jit key set -- and
        therefore recompiles -- stays pinned after warmup."""
        key = ("tpu-cohort", shape)
        bucket = self._buckets.get(key)
        if bucket is None:
            from .aoi_cohort import _CohortTPUBucket

            bucket = _CohortTPUBucket(
                shape, pipeline=self.pipeline, cross_tick=self.cross_tick,
                delta_staging=self.delta_staging, emit=self._resolve_emit(),
                paged=self.paged, fused=self.fused)
            self._buckets[key] = bucket
        return bucket

    def _solo_bucket(self, capacity: int):
        """One EXCLUSIVE single-space device bucket: the per-space
        baseline (``cohort="solo"``) and the ``aoi.cohort`` demotion
        target.  ``exclusive`` frees it with its space (release_space);
        ``cohort_solo`` marks it for :meth:`recohort` and maps its tier
        back to ``tpu`` under chip-loss evacuation."""
        self._cohort_serial += 1
        bucket = _TPUBucket(capacity, pipeline=self.pipeline,
                            cross_tick=self.cross_tick,
                            delta_staging=self.delta_staging,
                            emit=self._resolve_emit(), paged=self.paged,
                            fused=self.fused)
        bucket.exclusive = True
        bucket.cohort_solo = True
        self._buckets[(f"tpu-solo-{self._cohort_serial}", capacity)] = bucket
        return bucket

    def _solo_handle(self, capacity: int) -> SpaceAOIHandle:
        bucket = self._solo_bucket(capacity)
        slot = bucket.acquire_slot()
        h = SpaceAOIHandle("tpu", capacity, bucket, slot, requested="tpu")
        self._handles.add(h)
        return h

    def _restack_handle(self, h: SpaceAOIHandle, bucket, shape: int) -> None:
        """Move one live space onto ``bucket`` (capacity ``shape`` >= the
        space's) through the snapshot seam -- the join/leave primitive.
        Runs between flushes; undelivered events and a staged-but-
        undispatched tick are carried, so nothing drops or doubles.
        Snapshot padding is bit-exact: the grown tail is inactive and the
        predicate never reports inactive slots."""
        mig = getattr(h, "_migration", None)
        if mig is not None:
            mig.abort("space re-stacked mid-cover")
        old_bucket, old_slot = h.bucket, h.slot
        snap = AC.pad_snapshot(old_bucket.export_snapshot(old_slot), shape)
        staged = old_bucket._staged.pop(old_slot, None)
        slot = bucket.acquire_slot()
        bucket.import_snapshot(slot, snap)
        pending = old_bucket._events.pop(old_slot, None)
        if pending is not None:
            bucket._events[slot] = pending
        if staged is not None:
            bucket.stage(slot, staged)
        old_bucket.release_slot(old_slot)
        if getattr(old_bucket, "exclusive", False):
            for k, b in list(self._buckets.items()):
                if b is old_bucket:
                    del self._buckets[k]
        stack = getattr(h, "_policy_stack", None)
        if stack is not None and shape != h.capacity:
            stack.grow(shape)
        h.bucket, h.slot = bucket, slot
        h.capacity, h.backend = shape, "tpu"

    def cohort_join(self, h: SpaceAOIHandle) -> SpaceAOIHandle:
        """Stack a live space into the shared cohort bucket at its ladder
        shape (planner stack decision, or re-arming after a demotion).
        In place: the handle object survives, re-pointed."""
        if h.released:
            raise ValueError("space AOI handle already released")
        if self.mesh is not None:
            raise ValueError("cohorts are a single-chip tier")
        shape = AC.cohort_shape(h.capacity, self.cohort_ladder)
        if shape is None:
            raise ValueError(
                f"capacity {h.capacity} is past the cohort ladder "
                f"{self.cohort_ladder}")
        bucket = self._cohort_bucket(shape)
        if h.bucket is bucket:
            return h
        with _T.span("aoi.cohort.join"):
            self._restack_handle(h, bucket, shape)
        self.cohort_stats["cohort_joins"] += 1
        return h

    def cohort_leave(self, h: SpaceAOIHandle) -> SpaceAOIHandle:
        """Un-stack a live space onto its own solo bucket (planner
        keep-solo decision: e.g. one hot space must not gate its cohort's
        shared launch).  In place, like :meth:`cohort_join`."""
        if h.released:
            raise ValueError("space AOI handle already released")
        if not getattr(h.bucket, "cohort", False):
            return h
        with _T.span("aoi.cohort.leave"):
            self._restack_handle(h, self._solo_bucket(h.capacity),
                                 h.capacity)
        self.cohort_stats["cohort_leaves"] += 1
        return h

    def recohort(self) -> int:
        """Re-arm after ``aoi.cohort`` demotions: stack every space now
        sitting on a demoted/planner solo bucket back into its cohort.
        Returns the number of spaces moved.  (The fault seam stays
        one-shot per cohort bucket instance -- a fresh bucket probes the
        seam fresh, so a re-armed plan can fire again.)"""
        moved = 0
        for h in list(self._handles):
            if h.released or not getattr(h.bucket, "cohort_solo", False):
                continue
            self.cohort_join(h)
            moved += 1
        return moved

    def _demote_cohort(self, bucket) -> list:
        """The ``aoi.cohort`` seam fired at this bucket's dispatch (its
        shared program is suspect; nothing was staged to the device this
        tick): rebuild every member space onto its own solo bucket NOW,
        re-staging this tick's inputs, and return the fresh buckets still
        undispatched so flush() runs them under whichever phase
        discipline is active -- the republish is same-tick and bit-exact.
        """
        t0 = time.perf_counter()
        new_buckets: list = []
        with _T.span("aoi.cohort.demote"):
            for m in [m for m in self._migrations
                      if m.h.bucket is bucket or m.t.bucket is bucket]:
                m.abort("cohort demoting to per-space dispatch")
            staged = dict(bucket._staged)
            bucket._staged.clear()
            snaps = bucket.evacuate()
            for k, b in list(self._buckets.items()):
                if b is bucket:
                    del self._buckets[k]
            owners = {h.slot: h for h in self._handles
                      if h.bucket is bucket and not h.released}
            for slot in sorted(snaps):
                h = owners.get(slot)
                if h is None:
                    continue  # no live Space behind the slot
                nb = self._solo_bucket(h.capacity)
                ns = nb.acquire_slot()
                nb.import_snapshot(ns, snaps[slot])
                pending = bucket._events.pop(slot, None)
                if pending is not None:
                    nb._events[ns] = pending
                tick = staged.get(slot)
                if tick is not None:
                    nb.stage(ns, tick)
                h.bucket, h.slot = nb, ns
                self.cohort_stats["cohort_demoted_spaces"] += 1
                new_buckets.append(nb)
        self.migration_stats["migration_ms"] += (
            time.perf_counter() - t0) * 1e3
        return new_buckets

    def release_space(self, h: SpaceAOIHandle) -> None:
        mig = getattr(h, "_migration", None)
        if mig is not None:
            # a space released mid-cover rolls its migration back first --
            # the target slot must not outlive the space
            mig.abort("space released mid-cover")
        if not h.released:
            h.bucket.release_slot(h.slot)
            h.released = True
            if getattr(h.bucket, "exclusive", False):
                # per-space bucket (row-sharded): drop it so its device
                # state frees with the space
                for k, b in list(self._buckets.items()):
                    if b is h.bucket:
                        del self._buckets[k]

    def submit(self, h: SpaceAOIHandle, x, z, radius, active) -> None:
        """Stage one space's tick inputs (numpy arrays of length <= capacity)."""
        if h.released:
            raise ValueError("space AOI handle already released")
        mig = getattr(h, "_migration", None)
        if mig is not None:
            # double-cover: the migration target computes the same ticks
            # from the same inputs until CRC parity confirms the replay
            mig.on_submit(x, z, radius, active)
        h.bucket.stage(h.slot, (x, z, radius, active))

    def flush(self) -> None:
        """Execute all staged steps (one batched kernel per bucket); results
        are then available per space via :meth:`take_events` (one tick late
        when pipelined).

        Split-phase scheduler (docs/perf.md): dispatch EVERY bucket first
        (host pack + delta diff + H2D enqueue + kernel enqueue, never
        blocking on device values), then harvest in dispatch order -- so
        every bucket's kernel is in flight before the first fetch blocks,
        and bucket N+1's device work overlaps bucket N's host decode.
        Buckets iterate in sorted key order so dispatch/harvest order --
        and therefore the fired order of fault-seam occurrences -- is
        independent of space-creation interleaving.  ``flush_sched=False``
        forces the sequential baseline: each bucket dispatches AND
        harvests before the next starts."""
        for m in list(self._migrations):
            m.on_flush_begin()
        buckets = [self._buckets[k] for k in sorted(self._buckets)]
        if not self.flush_sched:
            for bucket in buckets:
                bucket.dispatch()
                if getattr(bucket, "_cohort_demote", False):
                    # aoi.cohort fired at dispatch (before any staging
                    # mutation): rebuild per-space and republish the SAME
                    # tick through the fresh solo buckets
                    for nb in self._demote_cohort(bucket):
                        nb.flush()
                    continue  # the torn-down cohort has nothing to harvest
                bucket.harvest()
        else:
            with _T.span("aoi.dispatch"):
                for bucket in buckets:
                    bucket.dispatch()
                demoting = [b for b in buckets
                            if getattr(b, "_cohort_demote", False)]
                if demoting:
                    for b in demoting:
                        for nb in self._demote_cohort(b):
                            nb.dispatch()
                    # re-list: demoted cohorts are gone, their solo
                    # replacements (already dispatched) must harvest
                    buckets = [self._buckets[k]
                               for k in sorted(self._buckets)]
            with _T.span("aoi.harvest"):
                for bucket in buckets:
                    bucket.harvest()
        if self._migrations:
            # double-cover verification: compare the event deltas both
            # homes produced this flush; swap/abort decisions happen here
            with _T.span("aoi.migrate.cover"):
                for m in list(self._migrations):
                    m.on_flush_end()
        evacuating = [k for k, b in self._buckets.items()
                      if getattr(b, "_evacuating", False)]
        for key in sorted(evacuating):
            self._evacuate_bucket(key)
        # interest-policy stacks evaluate LAST, after bucket harvest (and
        # after any evacuation re-pointed their handles): each staged
        # stack runs one fused step and accumulates its enter/leave diff
        # for take_events.  Stacks are per-space independent, so the
        # iteration order cannot affect results.
        staged = [h for h in self._handles
                  if getattr(h, "_policy_stack", None) is not None
                  and h._policy_stack.has_pending]
        if staged:
            with _T.span("aoi.interest"):
                for h in staged:
                    h._policy_stack.step()

    # -- chip-loss failover (docs/robustness.md) --------------------------

    @staticmethod
    def _tier_of(bucket) -> str:
        """Placement tier of a live bucket (the _create_handle vocabulary)."""
        if getattr(bucket, "cohort", False) \
                or getattr(bucket, "cohort_solo", False):
            # cohort + demoted-solo buckets are single-chip device tiers;
            # chip-loss evacuation re-homes their spaces onto the shared
            # tpu bucket at the same (ladder) capacity -- still stacked
            return "tpu"
        if getattr(bucket, "exclusive", False):
            return "rowshard"
        name = type(bucket).__name__
        if name == "_MeshTPUBucket":
            return "mesh"
        if name == "_TPUBucket":
            return "tpu"
        return ("cpu" if getattr(bucket, "_oracle_cls", None) is CPUAOIOracle
                else "cpp")

    def _evacuate_bucket(self, key) -> None:
        """The bucket's chip is LOST (``aoi.device`` seam, kind ``reset``
        -> faults.DeviceLost).  Its in-flight tick was already recovered
        host-side from (mirror, shadows) by the tier's ``_recover`` -- the
        bucket's host state IS the truth -- so rebuild every live space
        onto a fresh bucket of the same tier (a surviving device) through
        the snapshot/import machinery, carry undelivered events, and
        re-point the handles in place: no restart, no dropped tick, no
        lost or duplicated enter/leave events."""
        bucket = self._buckets[key]
        t0 = time.perf_counter()
        with _T.span("aoi.evacuate"):
            for m in [m for m in self._migrations
                      if m.h.bucket is bucket or m.t.bucket is bucket]:
                m.abort("bucket evacuating after device loss")
            tier = self._tier_of(bucket)
            snaps = bucket.evacuate()
            del self._buckets[key]
            owners = {h.slot: h for h in self._handles
                      if h.bucket is bucket and not h.released}
            for slot in sorted(snaps):
                h = owners.get(slot)
                if h is None:
                    continue  # no live Space behind the slot: nothing to save
                nh = self._create_handle(h.capacity, tier)
                nh.bucket.import_snapshot(nh.slot, snaps[slot])
                pending = bucket._events.pop(slot, None)
                if pending is not None:
                    nh.bucket._events[nh.slot] = pending
                # atomic ownership swap: the Space's handle object never
                # changes, it just points at the new home
                h.bucket, h.slot = nh.bucket, nh.slot
                nh.released = True  # shell handle; h owns the slot now
        self.migration_stats["evacuations"] += 1
        self.migration_stats["migration_ms"] += (
            time.perf_counter() - t0) * 1e3

    def has_pending(self) -> bool:
        """True when a pipelined bucket holds a dispatched-but-unharvested
        tick (the runtime must keep flushing until it drains)."""
        return any(
            getattr(self._buckets[k], "_inflight", None) is not None
            for k in sorted(self._buckets)
        )

    def _telemetry_collect(self):
        """Registry collector: bucket stats/perf summed across this
        engine's buckets (docs/observability.md metric catalog).
        ``calc_level`` reports the WORST bucket -- any demoted calculator
        should page, however many healthy ones sit next to it."""
        lbl = {"engine": str(self._telemetry_id)}
        stats: dict[str, float] = {}
        perf: dict[str, float] = {}
        calc_level = 0
        emit_path = 0
        page_occ = 0.0
        for b in (self._buckets[k] for k in sorted(self._buckets)):
            for k, v in getattr(b, "stats", {}).items():
                if k == "calc_level":
                    calc_level = max(calc_level, v)
                elif k == "emit_path":
                    # like calc_level: the WORST bucket -- one demoted emit
                    # path should page even among healthy neighbors
                    emit_path = max(emit_path, v)
                elif k == "page_occupancy":
                    # gauge, not a counter: the FULLEST pool -- the bucket
                    # closest to spilling is the one capacity planning
                    # must see
                    page_occ = max(page_occ, v)
                else:
                    stats[k] = stats.get(k, 0) + v
            for k, v in getattr(b, "perf", {}).items():
                perf[k] = perf.get(k, 0.0) + v
        cohorts = sum(1 for b in self._buckets.values()
                      if getattr(b, "cohort", False))
        cohort_spaces = sum(1 for h in self._handles
                            if not h.released
                            and getattr(h.bucket, "cohort", False))
        out = [Sample("aoi.buckets", "gauge", len(self._buckets), lbl,
                      "live AOI buckets in this engine"),
               Sample("aoi.cohorts", "gauge", cohorts, lbl,
                      "live cohort buckets (space-stacked planes)"),
               Sample("aoi.cohort_spaces", "gauge", cohort_spaces, lbl,
                      "spaces currently stacked into cohort buckets"),
               Sample("aoi.calc_level", "gauge", calc_level, lbl,
                      "worst calculator fallback level "
                      "(0=pallas 1=dense 2=host oracle)"),
               Sample("aoi.emit_path", "gauge", emit_path, lbl,
                      "worst emit-path fallback level "
                      "(0=native 1=vector 2=host decode)"),
               Sample("aoi.page_occupancy", "gauge", page_occ, lbl,
                      "fullest page pool at last harvest "
                      "(used/total pages; paged buckets only)")]
        for k in sorted(stats):
            out.append(Sample("aoi." + k, "counter", stats[k], lbl,
                              "summed per-bucket AOI stat"))
        for k in sorted(perf):
            out.append(Sample("aoi." + k.replace("_s", "_seconds"), "counter",
                              perf[k], lbl,
                              "cumulative per-phase flush time"))
        ms = self.migration_stats
        out.append(Sample("aoi.migrations", "counter", ms["migrations"], lbl,
                          "completed live space migrations"))
        out.append(Sample("aoi.evacuations", "counter", ms["evacuations"],
                          lbl, "bucket evacuations after chip loss"))
        out.append(Sample("aoi.migration_rollbacks", "counter",
                          ms["migration_rollbacks"], lbl,
                          "migrations aborted back to their source bucket"))
        out.append(Sample("aoi.migration_ms", "counter",
                          ms["migration_ms"], lbl,
                          "cumulative migration/evacuation wall time (ms)"))
        cs = self.cohort_stats
        out.append(Sample("aoi.cohort_joins", "counter", cs["cohort_joins"],
                          lbl, "spaces stacked into a cohort live"))
        out.append(Sample("aoi.cohort_leaves", "counter",
                          cs["cohort_leaves"], lbl,
                          "spaces un-stacked onto solo buckets"))
        out.append(Sample("aoi.cohort_demoted_spaces", "counter",
                          cs["cohort_demoted_spaces"], lbl,
                          "spaces rebuilt per-space by aoi.cohort "
                          "demotions"))
        return out

    def attach_interest(self, h: SpaceAOIHandle, policies,
                        mode: str | None = None):
        """Attach a composable interest-policy stack to a space
        (goworld_tpu/interest/): from here on the stack's fused step --
        radius AND team mask AND tier cadence AND line of sight -- owns
        the space's event stream (:meth:`take_events` returns the
        stack's diff), while the base bucket keeps carrying the radius
        state through migration/checkpoint/growth untouched.  A restore
        snapshot stashed on the handle (``_interest_snapshot``, set by
        checkpoint.restore_into) is imported automatically so policy
        state rides the pad_packet payload format end to end."""
        from ..interest import PolicyStack

        if getattr(h, "_policy_stack", None) is not None:
            raise ValueError("space already has an interest stack")
        stack = PolicyStack(h.capacity, policies,
                            mode=mode or self.interest_mode)
        snap = getattr(h, "_interest_snapshot", None)
        if snap is not None:
            stack.import_payload(snap)
            h._interest_snapshot = None
        h._policy_stack = stack
        return stack

    @staticmethod
    def interest_stack(h: SpaceAOIHandle):
        """The space's PolicyStack, or None (plain radius-only space)."""
        return getattr(h, "_policy_stack", None)

    def take_events(self, h: SpaceAOIHandle):
        """(enter_pairs, leave_pairs) for this space from the last flush."""
        stack = getattr(h, "_policy_stack", None)
        if stack is not None:
            # the stack owns the stream: drop the bucket's base-predicate
            # diff (the bucket still computes/carries base state -- that
            # is what migration double-cover and checkpoints verify)
            h.bucket.take_events(h.slot)
            return stack.take_events()
        return h.bucket.take_events(h.slot)

    def set_subscribed(self, h: SpaceAOIHandle, flag: bool) -> None:
        """Opt a space in/out of the per-tick event stream (see
        _Bucket.set_subscribed).  Spaces whose entities are all plain opt
        out: device backends then skip their extraction/fetch/decode
        entirely and their interest state is derived on demand."""
        mig = getattr(h, "_migration", None)
        if mig is not None:  # keep the double-cover target in lockstep
            mig.t.bucket.set_subscribed(mig.t.slot, flag)
        h.bucket.set_subscribed(h.slot, flag)

    def clear_entity(self, h: SpaceAOIHandle, entity_slot: int) -> None:
        """Erase one entity's row and column from the space's previous-tick
        interest state.  Called when an entity leaves the space: the runtime
        severs its interest pairs synchronously (departure events must fire
        the same tick), so the calculator must not re-emit them as diffs --
        and a reused slot must start clean."""
        mig = getattr(h, "_migration", None)
        if mig is not None:  # keep the double-cover target in lockstep
            mig.t.bucket.clear_entity(mig.t.slot, entity_slot)
        h.bucket.clear_entity(h.slot, entity_slot)
        stack = getattr(h, "_policy_stack", None)
        if stack is not None:
            stack.clear_entity(entity_slot)

    def grow_space(self, h: SpaceAOIHandle, new_capacity: int) -> SpaceAOIHandle:
        """Move a space to a larger-capacity bucket, carrying its interest
        state so the growth itself emits no enter/leave events.

        The packed layout depends on capacity (planar: bit positions shuffle
        when W changes), so the carry-over repacks via the boolean matrix.
        Growth is rare (capacity doubles), so the host-side repack is fine.
        """
        new_capacity = P.round_capacity(new_capacity)
        if new_capacity <= h.capacity:
            raise ValueError("grow_space requires a larger capacity")
        mig = getattr(h, "_migration", None)
        if mig is not None:
            # growth changes the packed layout mid-cover; roll the
            # migration back (zero loss) and let the controller retry
            mig.abort("space grown mid-cover")
        nh = self.create_space(new_capacity, h.requested or h.backend)
        # cohort routing may round the new home UP to its ladder shape;
        # repack to the capacity the new bucket actually allocates
        target = nh.capacity
        old_words = h.bucket.get_prev(h.slot)
        ratio = target // h.capacity
        if target == h.capacity * ratio and ratio & (ratio - 1) == 0:
            # power-of-two growth (every Space growth: capacity doubles):
            # packed word-level column remap, no dense matrix -- the dense
            # path is O(C^2) host BYTES, 17 GB at C=131072 (the oversized
            # capacities the row-sharded calculator serves)
            cap = h.capacity
            words = old_words
            while cap < target:
                words = P.repack_columns_double(words, cap)
                cap *= 2
            packed = np.zeros((target, words.shape[1]), np.uint32)
            packed[: h.capacity] = words
        else:
            m = P.unpack_rows(old_words, h.capacity)
            grown = np.zeros((target, target), bool)
            grown[: h.capacity, : h.capacity] = m
            packed = P.pack_rows(grown)
        nh.bucket.set_prev(nh.slot, packed)
        # carry undelivered events: growth can happen between flush() and
        # dispatch_aoi_events() (e.g. an on_enter_aoi hook spawns entities);
        # dropping them would permanently desync interest sets
        pending = h.bucket._events.pop(h.slot, None)
        if pending is not None:
            nh.bucket._events[nh.slot] = pending
        stack = getattr(h, "_policy_stack", None)
        if stack is not None:
            # the interest stack grows with the space: same planar column
            # remap as the base carry above, then it rides the NEW handle
            stack.grow(target)
            nh._policy_stack = stack
            h._policy_stack = None
        self.release_space(h)
        return nh


class _Bucket:
    """Slot-managed batch of spaces sharing a backend and capacity."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.W = P.words_per_row(capacity)
        self.n_slots = 0
        self._free: list[int] = []
        self._staged: dict[int, tuple] = {}
        self._events: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def acquire_slot(self) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            slot = self.n_slots
            self.n_slots += 1
            self._grow_to(self.n_slots)
        self._reset_slot(slot)
        return slot

    def release_slot(self, slot: int) -> None:
        self._free.append(slot)
        self._staged.pop(slot, None)
        self._events.pop(slot, None)

    def stage(self, slot: int, staged: tuple) -> None:
        self._staged[slot] = staged

    def take_events(self, slot: int):
        return self._events.pop(slot, (np.empty((0, 2), np.int32),) * 2)

    def set_subscribed(self, slot: int, flag: bool) -> None:
        """Event-stream subscription.  A slot whose space has no event
        consumers (all entities plain: no client, default hooks) may opt out
        of the per-tick event stream entirely -- its interest state stays in
        the packed device words, derived on demand (Space.derive_interests).
        Default: subscribed.  Host backends ignore this (their events are a
        free by-product of the sweep); device backends skip the extraction,
        fetch, and decode for opted-out slots."""

    def reset_emit_path(self) -> None:
        """Re-arm the configured emit path after an ``aoi.emit`` demotion
        (operator action, like reset_calc_chain -- demotion is sticky so a
        flapping native layer cannot oscillate).  No-op for host buckets,
        which have no emit seam."""
        req = getattr(self, "_emit_requested", None)
        if req is not None:
            self._emit = req
            self.stats["emit_path"] = AE.EMIT_LEVEL[req]

    # subclass API
    def _grow_to(self, n_slots: int) -> None:
        raise NotImplementedError

    def _reset_slot(self, slot: int) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def dispatch(self) -> None:
        """Phase 1 of the split flush (docs/perf.md): enqueue this tick's
        device work without blocking on device values.  Host-only buckets
        dispatch-and-complete inline -- the default delegates to
        :meth:`flush` -- so their harvest is a no-op.  Device buckets
        override both phases."""
        self.flush()

    def harvest(self) -> None:
        """Phase 2 of the split flush: fetch + decode whatever
        :meth:`dispatch` enqueued (no-op for inline buckets)."""

    def drain(self) -> None:
        """Deliver any pipelined tick still in flight (no-op by default)."""

    def peek_words(self, slot: int) -> np.ndarray | None:
        """Current interest words [C, W] for a slot WITHOUT forcing a device
        round trip -- the backing store for lazily derived interest sets
        (Space.derive_interests).  None when no cheap host copy exists yet
        (the caller then falls back to :meth:`get_prev`)."""
        return None

    def get_prev(self, slot: int) -> np.ndarray:
        """Previous-tick interest words [C, W] for state carry-over."""
        raise NotImplementedError

    def set_prev(self, slot: int, words: np.ndarray) -> None:
        raise NotImplementedError

    def clear_entity(self, slot: int, entity_slot: int) -> None:
        raise NotImplementedError


class _CPUBucket(_Bucket):
    """Host-side bucket; ``oracle_cls`` picks the python sweep oracle (the
    parity reference) or the native C++ sweep (ops.aoi_native, the
    production host calculator -- reference role: go-aoi XZList)."""

    def __init__(self, capacity: int, algorithm: str,
                 oracle_cls=CPUAOIOracle):
        super().__init__(capacity)
        self.algorithm = algorithm
        self._oracle_cls = oracle_cls
        self._oracles: list = []
        # last flushed inputs per slot (REFERENCES, not copies -- the host
        # hot path must not pay per-tick array copies; export_snapshot
        # copies on demand).  The migration snapshot's position packet is
        # built from these.
        self._last: dict[int, tuple] = {}
        # phase-attribution counters (seconds, cumulative; bench_engine
        # reads deltas) -- a perf_counter pair per flush, noise-level cost
        self.perf = {"calc_s": 0.0}

    def _grow_to(self, n_slots: int) -> None:
        while len(self._oracles) < n_slots:
            self._oracles.append(
                self._oracle_cls(self.capacity, self.algorithm)
            )

    def _reset_slot(self, slot: int) -> None:
        self._oracles[slot].reset()
        self._last.pop(slot, None)

    def flush(self) -> None:
        t0 = time.perf_counter()
        _ts = _T.t()
        for slot, (x, z, r, act) in self._staged.items():
            self._events[slot] = self._oracles[slot].step(x, z, r, act)
            self._last[slot] = (x, z, r, act)
        self._staged.clear()
        _T.lap("aoi.kernel", _ts)
        self.perf["calc_s"] += time.perf_counter() - t0

    def export_snapshot(self, slot: int) -> dict:
        """Live-migration wire image of one slot (docs/robustness.md): the
        last flushed inputs as a delta-staging packet + the previous-tick
        interest words.  Inputs are the staged REFERENCES -- callers
        migrate between ticks, after flush and before the next submit, so
        the arrays still hold the flushed values."""
        last = self._last.get(slot)
        if last is None:
            c = self.capacity
            last = (np.zeros(c, np.float32), np.zeros(c, np.float32),
                    np.zeros(c, np.float32), np.zeros(c, bool))
        x, z, r, act = last
        xx = np.zeros(self.capacity, np.float32)
        zz = np.zeros(self.capacity, np.float32)
        rr = np.zeros(self.capacity, np.float32)
        aa = np.zeros(self.capacity, bool)
        n = len(x)
        xx[:n], zz[:n], rr[:n], aa[:n] = x, z, r, act
        return _build_snapshot(self.capacity, xx, zz, rr, aa, True,
                               self._oracles[slot].prev_words)

    def import_snapshot(self, slot: int, snap: dict) -> None:
        """Replay a migration snapshot onto this slot: reconstruct the
        input arrays from the packet (so a later re-export round-trips)
        and seed the oracle's previous-tick words."""
        if snap["capacity"] != self.capacity:
            raise ValueError(
                f"snapshot capacity {snap['capacity']} != bucket "
                f"capacity {self.capacity}")
        x, z = _unpack_positions(snap)
        self._last[slot] = (x, z, snap["r"].copy(), snap["act"].copy())
        self.set_prev(slot, snap["words"])

    def peek_words(self, slot: int) -> np.ndarray:
        return self._oracles[slot].prev_words

    def get_prev(self, slot: int) -> np.ndarray:
        return self._oracles[slot].prev_words.copy()

    def set_prev(self, slot: int, words: np.ndarray) -> None:  # gwlint: allow[host-sync] -- CPU-backend bucket: state is already host-resident
        self._oracles[slot].prev_words = np.asarray(words, np.uint32).copy()

    def clear_entity(self, slot: int, entity_slot: int) -> None:
        pw = self._oracles[slot].prev_words
        pw[entity_slot, :] = 0
        w, b = P.word_bit_for_column(entity_slot, self.capacity)
        pw[:, w] &= np.uint32(~(np.uint32(1) << np.uint32(b)) & 0xFFFFFFFF)


class _TPUBucket(_Bucket):
    """Device-resident interest state [S, C, W]; one fused kernel per flush.

    S (slot count) grows by doubling; interest state is preserved across
    growth by zero-padding new slots.  Unstaged slots step with their previous
    inputs absent -- their rows are marked inactive so they emit leave events
    only if they had interests and were explicitly reset (slot reuse), never
    spontaneously: a space that skips a tick simply re-submits nothing and its
    previous words are carried forward untouched (active=False would wipe
    them, so unstaged slots are skipped via a host-side mask and their
    prev rows rewritten unchanged).

    ``pipeline=True`` double-buffers the flush (SURVEY §7 hard part (d)):
    ``flush()`` dispatches tick T's device step and then harvests tick T-1's
    results -- whose scalar+stream D2H transfers were issued asynchronously
    at T-1's dispatch with optimistically sized slices, so the wire time
    overlaps the whole host tick between the two flushes.  Events are
    therefore delivered ONE TICK LATE (the documented latency/throughput
    trade; parity is bit-exact modulo the shift -- tests/test_aoi_engine.py
    test_pipelined_flush_parity).  ``drain()`` harvests a pending tick
    without dispatching a new one (shutdown, state carry-over, tests).

    ``cross_tick=True`` (the engine's ``aoi_cross_tick``) requests the
    SAME one-tick deferral as the scheduler-level contract: tick T+1's
    pack + H2D + kernel enqueue overlaps tick T's harvest because the
    dispatched record parks one flush before delivering.  It composes
    idempotently with ``pipeline`` -- either flag (or both) defers by
    exactly one tick, so every flag combination stays bit-exact modulo
    the same single shift (tests/test_cross_tick.py).  Fault recovery is
    unchanged: a fault during T's harvest cannot corrupt T+1's already-
    dispatched state because _recover/_recover_harvest rebuild from the
    columnar host shadows and re-park synthetic host records
    (docs/robustness.md).
    """

    def __init__(self, capacity: int, pipeline: bool = False,
                 delta_staging: bool = True, emit: str = "vector",
                 paged: bool = False, cross_tick: bool = False,
                 fused: bool = False):
        super().__init__(capacity)
        self.pipeline = pipeline
        self.cross_tick = bool(cross_tick)
        self.delta_staging = delta_staging
        # fused steady tick (docs/perf.md "Fused tick", ROADMAP #3): when
        # eligible, the whole dispatch compiles into ONE program
        # (ops/aoi_fused: scatter + kernel + diff + extraction/paging),
        # so the steady cost is one enqueue + one D2H fetch.  Unfused is
        # the A/B baseline and the demotion target: an aoi.* seam firing
        # in the fused attempt falls through to the unfused flow in the
        # same call, counted in fused_demotions, bit-exact same-tick.
        self.fused = bool(fused)
        # paged ragged storage (docs/perf.md paged storage): the change
        # stream compacts into fixed-size pages from an on-device free
        # list (ops/aoi_pages) instead of the capped triples/chunk
        # buffers -- no global per-tick cap, so decode_overflow cannot
        # fire; bins the pool cannot serve spill to host (counted in
        # page_spills, republished same-tick bit-exact) and re-arm the
        # pool through _PageDecay
        self.paged = bool(paged)
        self._n_pages = 0           # pool size; sized at first dispatch
        self._page_free = None      # device free list [n_pages] int32
        self._pages: _PageDecay | None = None
        self._pred_pages = 64       # optimistic page prefetch (pipeline)
        # emit fan-out path (docs/perf.md): "native"/"vector" run the
        # device-resident triples decode (_fused_bucket_step_tri) and fan
        # out through ops/aoi_emit; "host" keeps the classic encoded-stream
        # fetch + host decode as the bit-exact oracle.  _emit_requested is
        # what reset_emit_path re-arms after an aoi.emit demotion.
        self._emit = emit
        self._emit_requested = emit
        self._inflight = None  # pending dispatch awaiting harvest
        # split-phase flush (docs/perf.md): dispatch() parks what harvest()
        # must do here -- ("inflight",) = drain the inflight record,
        # ("rec", rec) = harvest a specific record, ("oracle", slots) =
        # level-2 host compute deferred past the other buckets' dispatches
        self._sched: tuple | None = None
        # per-slot release epoch: a pipelined harvest must NOT publish
        # events for a slot released (and possibly reused) after its
        # dispatch -- the new occupant would replay the dead space's pairs
        self._slot_epoch: dict[int, int] = {}
        # mirror maintenance ops (clears/resets) issued while a dispatched
        # tick is still in flight: they postdate that tick's change stream,
        # so they must apply AFTER its XOR at harvest, not immediately --
        # else the XOR re-plants bits the clear just removed
        self._mirror_ops: list[tuple] = []
        import jax.numpy as jnp

        self._jnp = jnp
        self.s_max = 0
        self.prev = None  # [S, C, W] uint32 device array
        self._pending_reset: set[int] = set()
        self._pending_clear: list[tuple[int, int]] = []  # (slot, entity_slot)
        # adaptive extraction caps; a tick that exceeds them is recovered
        # host-side from the full diff and the caps grow for the next tick;
        # _CapDecay shrinks them back toward the steady state
        self._max_chunks = 4096
        self._kcap = 8
        self._caps = _CapDecay(nd_floor=4096)
        # triples-path extraction cap (native/vector emit): grows on a
        # counted overflow up to _TRI_MAX, decays back via _tri
        self._max_triples = 16384
        self._tri = _TriCapDecay(floor=16384)
        # optimistic triple-buffer prefetch rows for the pipelined path
        self._pred_tri = 2048
        # donated scratch buffers, keyed (s_n, mc, kcap); replaced by each
        # flush's returns (same device memory, in-place)
        self._scratch: dict[tuple, tuple] = {}
        # encode-side caps (instance attrs so overflow tests can shrink them)
        self._max_gaps = _MAX_GAPS
        self._max_exc = _MAX_EXC
        # optimistic prefetch sizes for the pipelined path (rows, escapes,
        # exceptions) -- refit to each harvested tick
        self._pred = (512, 64, 256)
        # host mirror of the interest words, enabled lazily on the first
        # peek_words (lazy interest-set derivation): one device fetch to
        # seed, then one vectorized XOR of each harvested tick's change
        # stream -- no per-tick fetches
        self._mirror: np.ndarray | None = None
        # slots opted OUT of the event stream (set_subscribed(False)):
        # their changes are masked out of the extraction on device, so
        # their mirror rows go stale -- tracked in _mirror_stale and
        # refreshed from device on the next peek of that slot
        self._unsub: set[int] = set()
        self._mirror_stale: set[int] = set()
        # delta staging (the _h2d role cache grown into full device
        # residency): persistent HOST SHADOWS of the staged inputs
        # [s_max, C] (+ sub [s_max]) and matching DEVICE copies in _dev.
        # The shadow and the device copy are kept BITWISE identical --
        # flush() diffs newly staged values against the shadow (uint32 bit
        # patterns, so NaN payloads and -0.0/0.0 cannot silently diverge)
        # and ships only a compact (row, col, x, z) packet
        # (ops/aoi_stage.py); _dev_stale names the roles whose device copy
        # no longer matches the shadow and must be fully re-uploaded
        # (grow/reset, r/act/sub change -- the full-restage fallbacks).
        self._hx = np.zeros((0, capacity), np.float32)
        self._hz = np.zeros((0, capacity), np.float32)
        self._hr = np.zeros((0, capacity), np.float32)
        self._hact = np.zeros((0, capacity), bool)
        self._hsub = np.ones(0, bool)
        self._dev: dict[str, object] = {}
        self._dev_stale: set[str] = {"xz", "ra", "sub"}
        # delta path bails to a full restage past this changed fraction:
        # scatter cost grows with the packet while the full upload is flat
        self._delta_max_frac = 0.25
        # -- fault tolerance (docs/robustness.md) ------------------------
        # With a fault plan active the mirror is kept EAGERLY from slot 0:
        # it is the durable copy of the interest state the rebuild path
        # re-uploads after a device loss.  (Without a plan it stays lazy --
        # no behavior change for fault-free runs; a real device fault then
        # recovers via a best-effort prev fetch / shadow recompute.)
        self._ft = faults.active()
        self._need_rebuild = False   # device prev dropped; re-upload next flush
        # chip-loss failover: True after a DeviceLost recovery -- the
        # engine rebuilds every live slot onto a fresh bucket at the end
        # of the current flush (docs/robustness.md)
        self._evacuating = False
        # calculator fallback chain: 0 = platform default (pallas on TPU),
        # 1 = dense formulation, 2 = host oracle (device never touched).
        # Each kernel-phase fault demotes one level; reset_calc_chain()
        # re-arms the device path.
        self._calc_level = 0
        self._fault_phase = "stage"
        self._cur_slots: list[int] = []
        # H2D attribution (bench artifact): cumulative wire bytes actually
        # shipped and how often the sparse-packet path won.  The fault
        # counters ride along: rebuilds = device-state drops recovered from
        # the durable copy, fallbacks = calculator demotions, host_ticks =
        # ticks computed by the host oracle (recovery or level-2 mode),
        # poisoned = control-scalar corruptions caught by validation.
        # emit-path additions: decode_overflow = ticks whose compact decode
        # overflowed its cap and fell back to a counted full recovery;
        # emit_path = the fan-out level actually in use (0=native 1=vector
        # 2=host decode), surfaced like calc_level as a max gauge.
        # paged-path additions: page_spills = bins (or whole ticks) the
        # page pool could not serve, re-read from the kept change grid and
        # republished same-tick (counted, never silent); page_occupancy =
        # used/total pages at the last harvest (gauge, worst bucket wins)
        # fused-path additions: fused_dispatches = steady ticks that ran
        # as one program, fused_demotions = fused attempts a seam fault
        # demoted to the unfused flow (same call, bit-exact)
        self.stats = {"h2d_bytes": 0, "delta_flushes": 0, "full_flushes": 0,
                      "rebuilds": 0, "fallbacks": 0, "host_ticks": 0,
                      "poisoned": 0, "calc_level": 0,
                      "decode_overflow": 0,
                      "page_spills": 0, "page_occupancy": 0.0,
                      "fused_dispatches": 0, "fused_demotions": 0,
                      "emit_path": AE.EMIT_LEVEL[emit]}
        # phase-attribution counters (seconds, cumulative): stage = host
        # pack + H2D enqueue + dispatch, fetch = synchronous D2H waits,
        # decode = stream decode + mirror upkeep, emit = event fan-out +
        # publish (triples path; the classic host path lumps expansion
        # into decode_s as before).  bench_engine reads deltas to
        # attribute engine ms/tick between host logic, wire, and decode.
        self.perf = {"stage_s": 0.0, "fetch_s": 0.0, "decode_s": 0.0,
                     "emit_s": 0.0}

    @property
    def _defer(self) -> bool:
        """One-tick event deferral in effect.  ``pipeline`` and
        ``cross_tick`` request the SAME deferral mechanics (park the
        dispatched record one flush, prefetch its D2H async), so either
        flag -- or both -- shifts delivery by exactly one tick and the
        parity contract stays a single shift for every combination."""
        return self.pipeline or self.cross_tick

    @property
    def _steady(self) -> bool:
        """No cap recompile pending (see _CapDecay/_TriCapDecay/_PageDecay;
        benchmarks read this)."""
        if self.paged:
            return self._pages is not None and self._pages.steady
        if self._emit != "host":
            return self._tri.steady
        return self._caps.steady

    def _grow_to(self, n_slots: int) -> None:
        jnp = self._jnp
        if n_slots <= self.s_max:
            return
        new_s = max(1, self.s_max)
        while new_s < n_slots:
            new_s *= 2
        if self._need_rebuild or self._calc_level >= 2:
            # device copy is already down: the mirror below is the durable
            # copy and grows host-side; the next rebuild uploads it grown
            self.prev = None
        else:
            try:
                faults.check("aoi.grow")
                new_prev = jnp.zeros((new_s, self.capacity, self.W),
                                     jnp.uint32)
                if self.prev is not None and self.s_max > 0:
                    new_prev = new_prev.at[: self.s_max].set(self.prev)
                self.prev = new_prev
            except Exception as e:
                if not _device_fault(e):
                    raise
                # allocation of the GROWN state failed; the old prev is
                # intact, so the durable copy seeds exactly, then grows
                # host-side with the rest of this method
                self._ensure_mirror()
                self.stats["rebuilds"] += 1
                self.prev = None
                self._need_rebuild = True
                from ..utils import gwlog

                gwlog.logger("gw.aoi").warning(
                    "bucket grow to %d slots hit a device fault (%s); "
                    "state held in the host mirror until the next flush "
                    "rebuilds", new_s, e)
        if self._mirror is not None:
            grown = np.zeros((new_s, self.capacity, self.W), np.uint32)
            grown[: self._mirror.shape[0]] = self._mirror
            self._mirror = grown
        elif self._ft:
            # fault-tolerant mode keeps the durable copy from the start
            # (a fresh bucket's interest state is all-zero, so no fetch)
            self._mirror = np.zeros((new_s, self.capacity, self.W),
                                    np.uint32)
        for name in ("_hx", "_hz", "_hr"):
            arr = getattr(self, name)
            grown = np.zeros((new_s, self.capacity), np.float32)
            grown[: arr.shape[0]] = arr
            setattr(self, name, grown)
        hact = np.zeros((new_s, self.capacity), bool)
        hact[: self._hact.shape[0]] = self._hact
        self._hact = hact
        hsub = np.ones(new_s, bool)
        hsub[: self._hsub.shape[0]] = self._hsub
        self._hsub = hsub
        # device copies are the old shape: full restage on the next flush
        self._dev.clear()
        self._dev_stale = {"xz", "ra", "sub"}
        self.s_max = new_s

    def _reset_slot(self, slot: int) -> None:
        self._pending_reset.add(slot)
        self._unsub.discard(slot)  # subscription is per-occupant; default on
        # the shadow must match what the next flush stages for this slot
        # (zeros until the new occupant stages); the device copies now
        # diverge -> full restage (the ISSUE's grow/reset fallback)
        self._hx[slot] = 0.0
        self._hz[slot] = 0.0
        self._hr[slot] = 0.0
        self._hact[slot] = False
        self._hsub[slot] = True
        self._dev_stale.update(("xz", "ra", "sub"))
        self._mirror_stale.discard(slot)  # mirror row is reset to truth below
        if self._mirror is not None:
            # immediate even with a tick in flight: the harvest XOR is
            # epoch-guarded, so a dead epoch's stream can no longer re-plant
            # bits over this reset, and derivations between now and the next
            # flush must already see the slot empty
            self._mirror_apply_now(("reset", slot))

    def set_subscribed(self, slot: int, flag: bool) -> None:
        if flag:
            self._unsub.discard(slot)
        else:
            self._unsub.add(slot)
        if slot < self._hsub.shape[0] and self._hsub[slot] != flag:
            self._hsub[slot] = flag
            self._dev_stale.add("sub")

    def peek_words(self, slot: int) -> np.ndarray:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        """Host mirror of the slot's interest words.  First call seeds the
        mirror with one device fetch (after draining any pipelined tick so
        mirror and delivered events agree); afterwards each harvest keeps it
        current with a vectorized XOR of the decoded change stream."""
        if self._mirror is None:
            self.drain()
            # explicit copy=True + order="C" are BOTH load-bearing: a fetched
            # device array can carry the TPU's tiled strides (a non-C mirror
            # would make the harvest's reshape-XOR write to a silent copy),
            # and on the cpu backend np.asarray is a zero-copy READ-ONLY
            # view (the XOR would raise)
            self._mirror = (np.zeros((self.s_max, self.capacity, self.W),
                                     np.uint32)
                            if self.prev is None
                            else np.array(self.prev, np.uint32, copy=True,
                                          order="C"))
        elif slot in self._mirror_stale:
            # the slot's changes were masked out of the stream while it was
            # unsubscribed: refresh its rows from the device truth (one
            # [C, W] slice fetch, on demand -- the whole point is that quiet
            # plain spaces never pay this unless someone actually asks).
            # flush() first so pending maintenance (resets/clears) reaches
            # prev before the read; drain() so the refreshed row and the
            # delivered events agree.
            self.flush()
            self.drain()
            if self.prev is not None:
                self._mirror[slot] = np.asarray(self.prev[slot])
            else:
                # device down (rebuild pending / oracle mode): the slot's
                # prev equals the predicate of its last staged inputs
                self._mirror[slot] = _packed_predicate(
                    self._hx[slot], self._hz[slot], self._hr[slot],
                    self._hact[slot])
            self._mirror_stale.discard(slot)
        return self._mirror[slot]

    def flush(self) -> None:
        """Monolithic flush = dispatch immediately followed by harvest (the
        forced-sequential baseline; AOIEngine's scheduler calls the phases
        directly to overlap buckets -- docs/perf.md)."""
        self.dispatch()
        self.harvest()

    def dispatch(self) -> None:
        """Phase 1: drain maintenance, pack + diff + H2D-enqueue this tick's
        inputs and enqueue the jitted kernel -- never blocking on device
        values (gwlint flush-phase rule).  What remains to be fetched is
        parked in ``_sched`` for :meth:`harvest`."""
        if self._sched is not None:
            # re-entrant flush (get_prev/peek_words mid-scheduler): complete
            # the previous phase pair before dispatching anew
            self.harvest()  # gwlint: allow[flush-phase] -- re-entrant flush drains the prior dispatch first
        if not self._staged and not self._pending_reset and not self._pending_clear:
            # pipelined: a tick with nothing new still delivers the pending
            # tick's events (trailing flush)
            if self._inflight is not None:
                self._sched = ("inflight",)
            return
        if self._calc_level >= 2:
            # calculator fallback chain bottom: host-oracle mode -- the
            # device is out of the loop; maintenance already reached the
            # mirror (its device queues just drain) and the host compute
            # itself defers to harvest so it overlaps other buckets'
            # device work under the scheduler
            self._pending_reset.clear()
            self._pending_clear.clear()
            if not self._staged:
                if self._inflight is not None:
                    self._sched = ("inflight",)
                return
            self._sched = ("oracle", self._restage_shadows())
            return
        try:
            self._dispatch_device()
        except Exception as e:
            if not _device_fault(e):
                raise
            self._recover(e)
            if isinstance(e, faults.DeviceLost):
                self._mark_evacuating()

    def harvest(self) -> None:
        """Phase 2: block on whatever :meth:`dispatch` parked -- the D2H
        fetch + decode of the encoded event stream (or the deferred host
        oracle tick).  A device fault surfacing here (async dispatch:
        kernel errors materialize at the blocking fetch) recovers via
        :meth:`_recover_harvest`."""
        sched, self._sched = self._sched, None
        if sched is None:
            return
        if sched[0] == "oracle":
            if self._inflight is not None:
                self._harvest()  # deliver T-1 before parking T (cadence)
            self._host_tick(sched[1])
            return
        rec = self._inflight if sched[0] == "inflight" else sched[1]
        if rec is None:
            return
        self._fault_phase = "harvest"
        try:
            if sched[0] == "inflight":
                self._harvest()
            else:
                self._harvest(rec)
        except Exception as e:
            if not _device_fault(e):
                raise
            self._recover_harvest(e, rec)

    def _dispatch_device(self) -> None:
        import jax.numpy as jnp

        c = self.capacity
        self._fault_phase = "stage"
        # device health probe: kind ``reset`` = the chip is LOST
        # (faults.DeviceLost) -- recovery must land on a different device,
        # so dispatch()'s handler marks the bucket evacuating after the
        # standard host-side tick recovery
        faults.check("aoi.device")
        self._rebuild_device()
        if self._pending_reset:
            idx = jnp.asarray(sorted(self._pending_reset), jnp.int32)
            DC.record()
            self.prev = self.prev.at[idx].set(jnp.uint32(0))
            self._pending_reset.clear()
        if self._pending_clear:
            # combine repeated (slot, word) column masks host-side so the
            # scatter indices are unique, then apply everything in ONE
            # dispatch (k clears used to cost 2k round trips)
            col_mask: dict[tuple[int, int], int] = {}
            rows = []
            for slot, e in self._pending_clear:
                w, b = P.word_bit_for_column(e, c)
                key = (slot, w)
                col_mask[key] = col_mask.get(key, 0xFFFFFFFF) & (
                    ~(1 << b) & 0xFFFFFFFF)
                rows.append((slot, e))
            self._pending_clear.clear()
            cols = [(s, w, m) for (s, w), m in col_mask.items()]

            def pad(seq):  # repeat the last entry up to a power of two
                n = 1
                while n < len(seq):
                    n *= 2
                return seq + [seq[-1]] * (n - len(seq))

            rows = pad(rows)
            cols = pad(cols)
            DC.record()
            self.prev = _batched_clear(
                self.prev,
                jnp.asarray([s for s, _ in rows], jnp.int32),
                jnp.asarray([e for _, e in rows], jnp.int32),
                jnp.asarray([s for s, _, _ in cols], jnp.int32),
                jnp.asarray([w for _, w, _ in cols], jnp.int32),
                jnp.asarray([m for _, _, m in cols], jnp.uint32),
            )
        if not self._staged:
            # maintenance-only tick: nothing dispatched, but a pending
            # pipelined tick still delivers -- at harvest time
            if self._inflight is not None:
                self._sched = ("inflight",)
            return

        t_stage0 = time.perf_counter()
        _ts = _T.t()
        slots = sorted(self._staged)
        s_n = len(slots)
        sl = np.array(slots, np.intp)
        # restage into the persistent host shadow; the previously staged
        # values are saved first (fancy index -> compact copies) so
        # _stage_inputs can diff the new tick against them
        old_x, old_z = self._hx[sl], self._hz[sl]
        old_r, old_act = self._hr[sl], self._hact[sl]
        self._restage_shadows()
        self._cur_slots = slots  # recovery needs them once _staged is gone

        slot_idx = jnp.asarray(slots, jnp.int32)
        tri_mode = self._emit != "host" and not self.paged
        if self.paged:
            # paged path (docs/perf.md paged storage): the change stream
            # compacts into pages from the device-resident free list; the
            # scratch key uses mc=-2 as the paged namespace.  The pool is
            # (re)sized here: first dispatch seeds the floor, spills grow
            # it (bounded by pool_ceiling), _PageDecay shrinks it back --
            # a size change just reinitializes the free list.
            nw = s_n * c * self.W
            bw = PG.bin_words_for(self.W)
            if self._pages is None:
                self._pages = _PageDecay(floor=PG.pool_floor(nw))
            # the decay's floor (not a recomputed one) sizes the first
            # pool, so tests can preset a tiny _PageDecay to force spills
            want = max(self._n_pages, self._pages.floor)
            if self._page_free is None or want != self._n_pages \
                    or self._page_free.shape[0] != want:
                self._n_pages = want
                self._page_free = jnp.arange(want, dtype=jnp.int32)
            key = (s_n, -2, self._n_pages)
        elif tri_mode:
            # triples path (docs/perf.md emit paths): the decode happens ON
            # DEVICE; harvest fetches [count, 3] triples + one scalar.  The
            # scratch key uses mc=-1 as the tri namespace (classic mc >= 512)
            mt = self._max_triples
            key = (s_n, -1, mt)
        else:
            n_chunks_total = s_n * c * self.W // _LANES
            mc = min(self._max_chunks, max(n_chunks_total, 512))
            key = (s_n, mc, self._kcap)
        scratch = self._scratch.pop(key, None)
        if scratch is None:
            # keep a few shape variants so alternating staged-slot counts
            # still reuse donated memory; evict oldest beyond that.  The
            # pipeline holds one extra set in flight, so the pool plus the
            # inflight record double-buffer naturally.
            while len(self._scratch) >= 4:
                self._scratch.pop(next(iter(self._scratch)))
            if self.paged:
                scratch = (
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.full((self._n_pages, PG.PAGE_WORDS), -1,
                             jnp.int32),
                    jnp.zeros((self._n_pages, PG.PAGE_WORDS), jnp.uint32),
                    jnp.zeros((self._n_pages, PG.PAGE_WORDS), jnp.uint32),
                )
            elif tri_mode:
                scratch = (
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.full((mt, 3), -1, jnp.int32),
                )
            else:
                scratch = (
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.zeros((s_n, c, self.W), jnp.uint32),
                    jnp.zeros((mc, self._kcap), jnp.uint32),
                    jnp.zeros((mc, self._kcap), jnp.uint32),
                    jnp.full((mc, self._kcap), -1, jnp.int32),
                    jnp.zeros(mc, jnp.int32),
                )
        sub = self._hsub[sl]
        if self._mirror is not None and not sub.all():
            self._mirror_stale.update(s for s in slots if s in self._unsub)
        if self.fused and self._dispatch_fused(
                slots, sl, slot_idx, key, scratch, sub, old_x, old_z,
                old_r, old_act, tri_mode, t_stage0, _ts):
            return
        self._stage_inputs(sl, old_x, old_z, old_r, old_act)
        _T.lap("aoi.stage", _ts)
        _tk = _T.t()
        self._fault_phase = "kernel"
        faults.check("aoi.kernel")
        all_unsub = not sub.any()
        if self.paged:
            DC.record()
            out = _fused_bucket_step_paged(
                self.prev, *scratch, self._page_free, slot_idx,
                self._dev["x"], self._dev["z"], self._dev["r"],
                self._dev["act"], self._dev["sub"],
                PG.PAGE_WORDS, bw, PG.MAX_SPILL,
                "cpu" if self._calc_level >= 1 else None
            )
            (self.prev, new, chg, pg, pc, pn, page_tab, self._page_free,
             spill_bins, scalars) = out
            _T.lap("aoi.kernel", _tk)
            if not all_unsub:
                scalars.copy_to_host_async()
                page_tab.copy_to_host_async()
                spill_bins.copy_to_host_async()
            rec = {
                "mode": "paged",
                "slots": slots, "s_n": s_n, "key": key,
                "n_pages": self._n_pages, "bin_words": bw,
                "epochs": [self._slot_epoch.get(s, 0) for s in slots],
                "scratch": (new, chg, pg, pc, pn),
                "page_tab": page_tab,
                "spill_bins": spill_bins,
                "scalars": scalars,
                "all_unsub": all_unsub,
                "prefetch": None,
            }
            if self._defer and not all_unsub:
                # optimistic page prefetch: the used prefix rides the wire
                # while the host runs the next tick; harvest refetches on
                # a misfit
                ndp = min(self._n_pages, self._pred_pages)
                sl_pg = (pg[:ndp], pc[:ndp], pn[:ndp])
                for a in sl_pg:
                    a.copy_to_host_async()
                rec["prefetch"] = (ndp, sl_pg)
            prev_rec, self._inflight = self._inflight, rec
            self.perf["stage_s"] += time.perf_counter() - t_stage0
            if self._defer:
                if prev_rec is not None:
                    self._sched = ("rec", prev_rec)
            else:
                self._sched = ("inflight",)
            return
        if tri_mode:
            DC.record()
            out = _fused_bucket_step_tri(
                self.prev, *scratch, slot_idx, self._dev["x"],
                self._dev["z"], self._dev["r"], self._dev["act"],
                self._dev["sub"], mt,
                "cpu" if self._calc_level >= 1 else None
            )
            (self.prev, new, chg, tri, scalars) = out
            _T.lap("aoi.kernel", _tk)
            if not all_unsub:
                scalars.copy_to_host_async()
            rec = {
                "mode": "tri",
                "slots": slots, "s_n": s_n, "key": key, "mt": mt,
                "epochs": [self._slot_epoch.get(s, 0) for s in slots],
                "scratch": (new, chg, tri),
                "scalars": scalars,
                "all_unsub": all_unsub,
                "prefetch": None,
            }
            if self._defer and not all_unsub:
                # optimistic triple prefetch: D2H rides the wire while the
                # host runs the next tick; harvest refetches on a misfit
                ndp = min(mt, self._pred_tri)
                sl_tri = tri[:ndp]
                sl_tri.copy_to_host_async()
                rec["prefetch"] = (ndp, sl_tri)
            prev_rec, self._inflight = self._inflight, rec
            self.perf["stage_s"] += time.perf_counter() - t_stage0
            if self._defer:
                if prev_rec is not None:
                    self._sched = ("rec", prev_rec)
            else:
                self._sched = ("inflight",)
            return
        DC.record()
        out = _fused_bucket_step(
            self.prev, *scratch, slot_idx, self._dev["x"], self._dev["z"],
            self._dev["r"], self._dev["act"], self._dev["sub"],
            mc, self._kcap, self._max_gaps, self._max_exc,
            "cpu" if self._calc_level >= 1 else None
        )
        (self.prev, new, chg, g_vals, g_nv, g_lane, g_csel,
         rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg, exc_new,
         scalars) = out
        _T.lap("aoi.kernel", _tk)
        if not all_unsub:
            scalars.copy_to_host_async()
        rec = {
            "slots": slots, "s_n": s_n, "key": key, "mc": mc,
            "kcap": self._kcap,
            "epochs": [self._slot_epoch.get(s, 0) for s in slots],
            "scratch": (new, chg, g_vals, g_nv, g_lane, g_csel),
            "streams": (rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
                        exc_new),
            "scalars": scalars,
            # every staged slot unsubscribed: the stream is empty BY
            # CONSTRUCTION (chg masked on device), so the harvest needs no
            # fetch at all -- not even the scalars (one tiny synchronous
            # wait still costs a tunnel RTT when the host tick is shorter
            # than the wire latency)
            "all_unsub": all_unsub,
            "prefetch": None,
        }
        if self._defer and not all_unsub:
            # optimistic prefetch at the recent ticks' observed stream sizes:
            # the D2H rides the wire while the host runs the next tick's
            # logic; the harvest refetches exact slices on a misfit (rare --
            # sizes move slowly in steady state).  An all-unsubscribed tick
            # skips it outright: its stream is empty by construction and the
            # harvest's nd==0 early-out never fetches.
            ndp = min(mc, self._pred[0])
            escp = min(self._max_gaps, self._pred[1])
            excp = min(self._max_exc, self._pred[2])
            slices = (rowb[:ndp], bitpos[:ndp], woff[:ndp],
                      esc_rows[:escp], exc_gidx[:excp], exc_chg[:excp],
                      exc_new[:excp])
            for a in slices:
                a.copy_to_host_async()
            rec["prefetch"] = (ndp, escp, excp, slices)
        prev_rec, self._inflight = self._inflight, rec
        self.perf["stage_s"] += time.perf_counter() - t_stage0
        if self._defer:
            # tick T dispatched; T-1's record (whose D2H was prefetched at
            # its own dispatch) harvests in phase 2
            if prev_rec is not None:
                self._sched = ("rec", prev_rec)
        else:
            self._sched = ("inflight",)

    def _dispatch_fused(self, slots, sl, slot_idx, key, scratch, sub,
                        old_x, old_z, old_r, old_act, tri_mode,
                        t_stage0, _ts) -> bool:
        """Attempt the ONE-DISPATCH fused tick (ops/aoi_fused, ROADMAP
        #3): packet scatter + kernel + diff + extraction/paging as a
        single jitted program, so the steady tick is one enqueue + one
        D2H fetch.  Returns True when the tick was dispatched fused
        (the caller's unfused flow is skipped), False to fall through.

        Two distinct False paths, by design:

        * ineligible -- the tick is not a steady delta tick (stale
          device roles, r/act changed, diff too large, classic host-emit
          mode, device down): silent fall-through, the unfused path IS
          the right program for it;
        * demoted -- an ``aoi.delta``/``aoi.kernel`` seam fault fired in
          the fused attempt: counted in ``fused_demotions`` and fall
          through BEFORE any device mutation, so the unfused flow
          (whose seam occurrence was consumed by the fused attempt)
          runs clean in the same call -- same-tick, bit-exact.
        """
        s_n = len(slots)
        if not (tri_mode or self.paged):
            return False  # classic host-emit stream has no fused program
        if (not self.delta_staging or self._dev_stale
                or self._calc_level >= 2 or self._need_rebuild):
            return False
        if any(role not in self._dev
               for role in ("x", "z", "r", "act", "sub")):
            return False
        new_x, new_z = self._hx[sl], self._hz[sl]
        if not (np.array_equal(self._hr[sl], old_r)
                and np.array_equal(self._hact[sl], old_act)):
            return False  # r/act moved: full-restage tick, unfused
        diff = (new_x.view(np.uint32) != old_x.view(np.uint32)) \
            | (new_z.view(np.uint32) != old_z.view(np.uint32))
        n_changed = np.count_nonzero(diff)
        if n_changed > self._delta_max_frac * diff.size:
            return False  # mass movement: full restage beats the scatter
        try:
            if n_changed:
                faults.check("aoi.delta")
            self._fault_phase = "kernel"
            faults.check("aoi.kernel")
        except Exception as e:
            if not _device_fault(e):
                raise
            self.stats["fused_demotions"] += 1
            self._fault_phase = "stage"
            return False
        if n_changed:
            rows, cols = np.nonzero(diff)
            pkt = AS.pad_packet(sl[rows], cols, new_x[rows, cols],
                                new_z[rows, cols],
                                page_granular=self.paged)
            self.stats["h2d_bytes"] += AS.packet_nbytes(*pkt)
        else:
            zi = np.zeros(0, np.int32)
            zf = np.zeros(0, np.float32)
            pkt = (zi, zi, zf, zf)  # zero movers: in-program no-op scatter
        self.stats["delta_flushes"] += 1
        _T.lap("aoi.stage", _ts)
        _tk = _T.t()
        all_unsub = not sub.any()
        platform = "cpu" if self._calc_level >= 1 else None
        DC.record()
        if self.paged:
            bw = PG.bin_words_for(self.W)
            out = AF.fused_paged_step(
                self.prev, *scratch, self._page_free, self._dev["x"],
                self._dev["z"], *pkt, slot_idx, self._dev["r"],
                self._dev["act"], self._dev["sub"], PG.PAGE_WORDS, bw,
                PG.MAX_SPILL, platform)
            (self.prev, new, chg, pg, pc, pn, self._page_free, bundle,
             self._dev["x"], self._dev["z"]) = out
            _T.lap("aoi.kernel", _tk)
            _T.lap("aoi.fused", _tk)
            if not all_unsub:
                bundle.copy_to_host_async()
            rec = {
                "mode": "paged",
                "slots": slots, "s_n": s_n, "key": key,
                "n_pages": self._n_pages, "bin_words": bw,
                "epochs": [self._slot_epoch.get(s, 0) for s in slots],
                "scratch": (new, chg, pg, pc, pn),
                # one compact int32 vector replaces the page_tab /
                # spill_bins / scalars triple-fetch of the unfused
                # harvest (_harvest_paged slices it back apart)
                "bundle": bundle,
                "page_tab": None, "spill_bins": None, "scalars": None,
                "all_unsub": all_unsub,
                "prefetch": None,
            }
            if self._defer and not all_unsub:
                ndp = min(self._n_pages, self._pred_pages)
                sl_pg = (pg[:ndp], pc[:ndp], pn[:ndp])
                for a in sl_pg:
                    a.copy_to_host_async()
                rec["prefetch"] = (ndp, sl_pg)
        else:
            mt = self._max_triples
            out = AF.fused_tri_step(
                self.prev, *scratch, self._dev["x"], self._dev["z"],
                *pkt, slot_idx, self._dev["r"], self._dev["act"],
                self._dev["sub"], mt, platform)
            (self.prev, new, chg, tri, scalars,
             self._dev["x"], self._dev["z"]) = out
            _T.lap("aoi.kernel", _tk)
            _T.lap("aoi.fused", _tk)
            if not all_unsub:
                scalars.copy_to_host_async()
            rec = {
                "mode": "tri",
                "slots": slots, "s_n": s_n, "key": key, "mt": mt,
                "epochs": [self._slot_epoch.get(s, 0) for s in slots],
                "scratch": (new, chg, tri),
                "scalars": scalars,
                "all_unsub": all_unsub,
                "prefetch": None,
            }
            if self._defer and not all_unsub:
                ndp = min(mt, self._pred_tri)
                sl_tri = tri[:ndp]
                sl_tri.copy_to_host_async()
                rec["prefetch"] = (ndp, sl_tri)
        self.stats["fused_dispatches"] += 1
        prev_rec, self._inflight = self._inflight, rec
        self.perf["stage_s"] += time.perf_counter() - t_stage0
        if self._defer:
            if prev_rec is not None:
                self._sched = ("rec", prev_rec)
        else:
            self._sched = ("inflight",)
        return True

    def drain(self) -> None:
        """Harvest a pending pipelined tick without dispatching a new one
        (shutdown, state carry-over, tests)."""
        self.harvest()
        if self._inflight is not None:
            self._harvest()

    # -- fault recovery (docs/robustness.md) -----------------------------
    #
    # The durable copies are the host shadows (_hx/_hz/_hr/_hact/_hsub --
    # bitwise identical to the device inputs by the delta-staging contract)
    # plus the mirror (the XOR-maintained host copy of the packed interest
    # words).  On any device-side fault the bucket (1) delivers the tick
    # already in flight (its buffers predate the fault), (2) recomputes the
    # faulted tick on the host from (mirror, shadows) -- the host predicate
    # is bit-exact with every device backend, and np.nonzero's ascending
    # flat order matches the device chunk extraction's, so the recovered
    # event stream is bit-identical -- and (3) drops all device state; the
    # next flush re-uploads prev from the mirror and full-restages inputs.

    def _restage_shadows(self) -> list[int]:
        """Copy staged tick inputs into the persistent host shadows (pure
        host work; shared by the device path and fault recovery)."""
        slots = sorted(self._staged)
        for slot in slots:
            sx, sz, sr, sa = self._staged[slot]
            n = len(sx)
            self._hx[slot, :n] = sx
            self._hx[slot, n:] = 0.0
            self._hz[slot, :n] = sz
            self._hz[slot, n:] = 0.0
            self._hr[slot, :n] = sr
            self._hr[slot, n:] = 0.0
            self._hact[slot, :n] = sa
            self._hact[slot, n:] = False
        self._staged.clear()
        return slots

    def _rebuild_device(self) -> None:
        """Re-upload the packed interest state from the durable host mirror
        after a device loss (deferred to flush so a dead device is retried
        at tick cadence, not in the failure handler)."""
        if not self._need_rebuild:
            return
        self._need_rebuild = False
        self.prev = self._jnp.asarray(self._mirror)
        self.stats["h2d_bytes"] += self._mirror.nbytes

    def reset_calc_chain(self) -> None:
        """Re-arm the device calculator after fallback (operator action --
        demotion is sticky so a flapping device cannot oscillate)."""
        self._calc_level = 0
        self.stats["calc_level"] = 0
        if self.prev is None and self.s_max:
            self._ensure_mirror()
            self._need_rebuild = True

    def _ensure_mirror(self) -> None:  # gwlint: allow[host-sync] -- fault-recovery path, not the steady tick
        """Make the host mirror exist.  Fault-tolerant buckets keep it from
        slot 0; otherwise seed it from the still-live device prev, or -- if
        the device is truly dead -- recompute from the input shadows (exact
        for every slot whose prev equals the predicate of its last staged
        inputs; seeded-then-unstepped slots lose their seed, loudly)."""
        if self._mirror is not None:
            return
        try:
            self._mirror = (
                np.zeros((self.s_max, self.capacity, self.W), np.uint32)
                if self.prev is None
                else np.array(self.prev, np.uint32, copy=True, order="C"))
        except Exception:
            from ..utils import gwlog

            gwlog.logger("gw.aoi").warning(
                "device prev unreadable during recovery; rebuilding the "
                "mirror from the input shadows (derived state of cleared/"
                "seeded slots may lag until their next stage)")
            m = np.empty((self.s_max, self.capacity, self.W), np.uint32)
            for s in range(self.s_max):
                m[s] = _packed_predicate(self._hx[s], self._hz[s],
                                         self._hr[s], self._hact[s])
            self._mirror = m

    def _refresh_stale_rows(self) -> None:
        """Recompute mirror rows that went stale while unsubscribed: a
        slot's prev equals the predicate of its last staged inputs (its
        shadows), so the recompute is exact up to post-stage clears
        (documented limitation; resubscription resyncs)."""
        for s in sorted(self._mirror_stale):
            self._mirror[s] = _packed_predicate(
                self._hx[s], self._hz[s], self._hr[s], self._hact[s])
        self._mirror_stale.clear()

    def _recover(self, e: BaseException) -> None:  # gwlint: allow[flush-phase] -- fault recovery: the device is gone, host sync is the point
        """Device fault mid-flush: deliver the inflight tick, recompute the
        faulted tick host-side (bit-exact), drop device state."""
        from ..utils import gwlog

        self.stats["rebuilds"] += 1
        if self._fault_phase == "kernel" and self._calc_level < 2:
            # the calculator itself failed: demote one level down the
            # chain (pallas -> dense -> host oracle)
            self._calc_level += 1
            self.stats["fallbacks"] += 1
            self.stats["calc_level"] = self._calc_level
        gwlog.logger("gw.aoi").warning(
            "AOI bucket (cap %d) device fault during %s: %s -- recovering "
            "tick on host (calc level %d)",
            self.capacity, self._fault_phase, e, self._calc_level)
        # 1. the tick dispatched LAST flush finished before this fault; its
        # buffers are intact, so it delivers on its normal schedule
        if self._inflight is not None:
            try:
                self._harvest()
            except Exception as he:  # the device died mid-harvest too
                gwlog.logger("gw.aoi").warning(
                    "inflight tick unharvestable during recovery (%s); "
                    "its events are lost", he)
                self._inflight = None
        # 2. make the durable copy exist, and land any maintenance that
        # never reached the device (idempotent re-apply otherwise)
        self._ensure_mirror()
        for s in sorted(self._pending_reset):
            self._mirror_apply_now(("reset", s))
        for s, ent in self._pending_clear:
            self._mirror_apply_now(("clear", s, ent))
        self._pending_reset.clear()
        self._pending_clear.clear()
        # 3. the faulted tick's inputs are (or now land) in the shadows
        slots = self._restage_shadows() if self._staged else self._cur_slots
        self._cur_slots = []
        # 4. device state is gone; the next flush rebuilds from the mirror
        self.prev = None
        self._dev.clear()
        self._dev_stale = {"xz", "ra", "sub"}
        self._scratch.clear()
        self._need_rebuild = self._calc_level < 2
        # 5. compute the faulted tick on the host
        if slots:
            self._host_tick(slots)

    def _recover_harvest(self, e: BaseException, rec: dict) -> None:  # gwlint: allow[flush-phase] -- fault recovery: the device is gone, host sync is the point
        """Device fault surfacing at HARVEST time (split-phase flush: the
        blocking fetch is where async kernel/transfer errors materialize).
        The faulted record's stream is unrecoverable from the device, but
        the durable copies bracket it exactly: the mirror still holds the
        state BEFORE the record's tick (its XOR never applied) and the
        shadows hold the newest staged inputs -- so one host predicate pass
        regenerates the lost events as a single coalesced diff, published
        immediately in place of the record's due delivery (bit-exact for
        the non-pipelined path; pipelined, the faulted tick and the one
        dispatched after it coalesce -- docs/robustness.md)."""
        from ..utils import gwlog

        self.stats["rebuilds"] += 1
        if _kernelish_fault(e) and self._calc_level < 2:
            self._calc_level += 1
            self.stats["fallbacks"] += 1
            self.stats["calc_level"] = self._calc_level
        gwlog.logger("gw.aoi").warning(
            "AOI bucket (cap %d) device fault during harvest: %s -- "
            "regenerating the tick's events on host (calc level %d)",
            self.capacity, e, self._calc_level)
        # a host-synthetic record cannot fault here (its harvest never
        # touches the device), but stay defensive: its events and mirror
        # effects are already final, so just re-publish its payload
        if rec.get("host"):
            chg_vals, ent_vals, gidx, s_n = rec["payload"]
            self._publish(rec["slots"], rec["epochs"], chg_vals, ent_vals,
                          gidx, s_n)
            rec_slots: list[int] = []
        else:
            rec_slots = rec["slots"]
        # the record dispatched AFTER the faulted one (pipelined) is on the
        # same dead device; fold its slots into the recompute.  A synthetic
        # inflight stays parked -- its mirror effects already landed and
        # its delivery schedule is unchanged.
        newest, self._inflight = self._inflight, None
        host_rec = None
        if newest is not None:
            if newest.get("host"):
                host_rec = newest
            else:
                rec_slots = sorted(set(rec_slots) | set(newest["slots"]))
        self._ensure_mirror()
        # mirror maintenance that was deferred behind the (now lost) stream
        # XOR, plus device-queue maintenance that never reached prev: land
        # everything on the mirror (idempotent)
        if self._mirror_ops:
            ops, self._mirror_ops = self._mirror_ops, []
            for op in ops:
                if self._slot_epoch.get(op[1], 0) == op[-1]:
                    self._mirror_apply_now(op[:-1])
        for s in sorted(self._pending_reset):
            self._mirror_apply_now(("reset", s))
        for s, ent in self._pending_clear:
            self._mirror_apply_now(("clear", s, ent))
        self._pending_reset.clear()
        self._pending_clear.clear()
        if self._staged:  # defensive: inputs staged between the phases
            rec_slots = sorted(set(rec_slots) | set(self._restage_shadows()))
        self._cur_slots = []
        # device state is gone; the next dispatch rebuilds from the mirror
        self.prev = None
        self._dev.clear()
        self._dev_stale = {"xz", "ra", "sub"}
        self._scratch.clear()
        self._page_free = None  # paged free list reinits at next dispatch
        self._need_rebuild = self._calc_level < 2
        if rec_slots:
            self._host_tick(rec_slots, publish_now=True)
        self._inflight = host_rec

    def _host_tick(self, slots: list[int], publish_now: bool = False) -> None:
        """One bucket tick on the host from the durable copies, bit-exact
        with the device step: new = predicate(shadows) per staged slot,
        chg = new XOR mirror (masked for unsubscribed slots), and the
        event stream in np.nonzero's ascending flat order -- exactly the
        device chunk-extraction order (the cap-overflow recovery path in
        _harvest decodes the same way).  ``publish_now`` skips the
        pipelined one-tick-late parking: harvest-time recovery substitutes
        this tick for the faulted record's due delivery."""
        c, W = self.capacity, self.W
        s_n = len(slots)
        self.stats["host_ticks"] += 1
        _th = _T.t()
        self._refresh_stale_rows()
        sl = np.array(slots, np.intp)
        sub = self._hsub[sl]
        new = np.empty((s_n, c, W), np.uint32)
        for i, s in enumerate(slots):
            new[i] = _packed_predicate(self._hx[s], self._hz[s],
                                       self._hr[s], self._hact[s])
        chg = new ^ self._mirror[sl]
        chg[~sub] = 0
        flat = chg.reshape(-1)
        gidx = np.nonzero(flat)[0]
        chg_vals = flat[gidx]
        ent_vals = chg_vals & new.reshape(-1)[gidx]
        self._mirror[sl] = new
        epochs = [self._slot_epoch.get(s, 0) for s in slots]
        if self._defer and not publish_now:
            # deferred cadence (pipeline/cross_tick): events are delivered
            # one tick late, so a recovered tick parks as a synthetic
            # inflight record and publishes at the NEXT flush, exactly like
            # a device tick
            self._inflight = {"host": True, "slots": slots,
                              "epochs": epochs,
                              "payload": (chg_vals, ent_vals, gidx, s_n)}
        else:
            self._publish(slots, epochs, chg_vals, ent_vals, gidx, s_n)
        _T.lap("aoi.host_tick", _th)

    def _harvest(self, rec=None) -> None:  # gwlint: allow[host-sync] -- THE per-tick drain point: harvests kernel outputs once per flush
        """Fetch + decode one dispatched tick's event stream and publish its
        per-slot events.  ``rec=None`` harvests (and clears) the inflight
        record."""
        if rec is None:
            rec, self._inflight = self._inflight, None
        if rec.get("host"):
            # synthetic record parked by fault recovery / oracle mode: the
            # events were computed host-side at its tick; only the
            # pipelined one-tick-late delivery remained
            chg_vals, ent_vals, gidx, s_n = rec["payload"]
            self._publish(rec["slots"], rec["epochs"], chg_vals, ent_vals,
                          gidx, s_n)
            self._apply_deferred_mirror_ops()
            return
        if rec.get("mode") == "paged":
            self._harvest_paged(rec)
            return
        if rec.get("mode") == "tri":
            self._harvest_tri(rec)
            return
        slots, s_n, mc = rec["slots"], rec["s_n"], rec["mc"]
        kcap = rec["kcap"]
        c = self.capacity
        (new, chg, g_vals, g_nv, g_lane, g_csel) = rec["scratch"]
        (rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
         exc_new) = rec["streams"]
        # ONE tiny fetch for all control scalars (each synchronous fetch
        # pays a round trip when the chip is reached over a network tunnel);
        # under the pipeline it was issued async at dispatch and is local by
        # now
        faults.check("aoi.fetch")  # stallable: a delayed host sync
        t_f0 = time.perf_counter()
        _tf = _T.t()
        poisoned = False
        if rec.get("all_unsub"):
            nd = mcc = base_row = n_esc = exc_n = 0
        else:
            raw = faults.filter("aoi.scalars", np.asarray(rec["scalars"]))
            nd, mcc, base_row, n_esc, exc_n = (int(v) for v in raw)
            nw = s_n * c * self.W
            if not (0 <= nd <= nw // _LANES and 0 <= mcc <= _LANES
                    and 0 <= n_esc <= nw and 0 <= exc_n <= nw
                    and 0 <= base_row <= nw // _LANES):
                # garbage control scalars (a kernel writing NaN-adjacent
                # junk): distrust the encoded stream wholesale and recover
                # this tick from the raw diff grids riding the same record
                from ..utils import gwlog

                self.stats["poisoned"] += 1
                gwlog.logger("gw.aoi").warning(
                    "AOI control scalars failed validation "
                    "(nd=%d mcc=%d base=%d esc=%d exc=%d); recovering the "
                    "tick from the raw diff grids", nd, mcc, base_row,
                    n_esc, exc_n)
                poisoned = True
                nd = mcc = base_row = n_esc = exc_n = 0
        shrink = (None if poisoned else
                  self._caps.observe(nd, mcc, self._max_chunks, self._kcap))
        if shrink is not None:
            self._max_chunks, self._kcap = shrink
        if poisoned:
            # full-diff recovery (same shape as the cap-overflow branch,
            # without growing the caps off corrupted values)
            chg_h = np.asarray(chg).reshape(-1)
            new_h = np.asarray(new).reshape(-1)
            gidx = np.nonzero(chg_h)[0]
            chg_vals = chg_h[gidx]
            ent_vals = chg_vals & new_h[gidx]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
        elif nd == 0 and exc_n == 0:
            # quiet tick (or every staged slot unsubscribed): the stream is
            # empty by construction -- the scalars above are the ONLY fetch
            chg_vals = np.empty(0, np.uint32)
            ent_vals = np.empty(0, np.uint32)
            gidx = np.empty(0, np.int64)
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
        elif nd > mc or mcc > kcap:
            # caps exceeded: recover this tick from the full diff, then grow
            # the caps so the next tick extracts on device again
            self.stats["decode_overflow"] += 1
            self._max_chunks = max(self._max_chunks, 2 * nd)
            # a chunk holds at most _LANES nonzero words
            self._kcap = min(max(self._kcap, 2 * mcc), _LANES)
            self._caps.reset_after_growth()
            chg_h = np.asarray(chg).reshape(-1)
            new_h = np.asarray(new).reshape(-1)
            gidx = np.nonzero(chg_h)[0]
            chg_vals = chg_h[gidx]
            ent_vals = chg_vals & new_h[gidx]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
        elif n_esc > self._max_gaps or exc_n > self._max_exc:
            # encode overflow (pathological churn): rebuild from the raw
            # grids kept on device
            self.stats["decode_overflow"] += 1
            ndp = min(mc, -(-max(nd, 1) // 512) * 512)
            slices = (g_vals[:ndp], g_nv[:ndp], g_lane[:ndp], g_csel[:ndp])
            for a in slices:
                a.copy_to_host_async()
            vh, nh, lh, ch = (np.asarray(a) for a in slices)
            valid = lh >= 0
            chg_vals = vh[valid]
            ent_vals = chg_vals & nh[valid]
            gidx = (ch[:, None].astype(np.int64) * _LANES + lh)[valid]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
        else:
            # the common path fetches the ENCODED stream: ~5 B per dirty
            # chunk + 12 B per exception, overlapped slice transfers
            pf = rec["prefetch"]
            if pf is not None and pf[0] >= nd and pf[1] >= n_esc \
                    and pf[2] >= exc_n:
                hb = [np.asarray(a) for a in pf[3]]
            else:
                ndp = min(mc, -(-max(nd, 1) // 128) * 128)
                escp = min(self._max_gaps, -(-max(n_esc, 1) // 64) * 64)
                excp = min(self._max_exc, -(-max(exc_n, 1) // 256) * 256)
                slices = (rowb[:ndp], bitpos[:ndp], woff[:ndp],
                          esc_rows[:escp], exc_gidx[:excp], exc_chg[:excp],
                          exc_new[:excp])
                for a in slices:
                    a.copy_to_host_async()
                hb = [np.asarray(a) for a in slices]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
            t_f0 = time.perf_counter()
            _td = _T.t()
            chg_vals, ent_vals, gidx = EV.decode_row_stream(
                hb[0], hb[1], hb[2].astype(np.uint16), base_row, nd,
                _LANES, hb[3], hb[4], hb[5], hb[6])
            self.perf["decode_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.diff", _td)
        t_f0 = time.perf_counter()
        _td = _T.t()
        # refit the next dispatch's optimistic prefetch to this tick
        self._pred = (
            max(512, -(-nd * 5 // 4 // 128) * 128),
            max(64, -(-(n_esc + 1) * 3 // 2 // 64) * 64),
            max(256, -(-(exc_n + 1) * 5 // 4 // 256) * 256),
        )
        self._mirror_xor_stream(slots, rec["epochs"], gidx, chg_vals)
        # the harvested scratch set returns to the pool for reuse
        self._scratch.setdefault(rec["key"], rec["scratch"])
        self._publish(slots, rec["epochs"], chg_vals, ent_vals, gidx, s_n)
        self.perf["decode_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.diff", _td)

    def _mirror_xor_stream(self, slots, epochs, gidx, chg_vals) -> None:  # gwlint: allow[host-sync] -- harvest-phase mirror upkeep on already-fetched host arrays
        """Apply one harvested word stream to the host mirror (then run the
        deferred maintenance ops that postdate it)."""
        if self._mirror is None:
            return
        if len(gidx):
            # stream entries are whole words with unique indices, so one
            # fancy-index XOR applies the tick exactly.  Rows whose slot
            # was released since this tick's dispatch are skipped -- the
            # same epoch guard that drops the dead space's events; a
            # reused slot's mirror was already reset at re-acquire and
            # must not have the dead stream XORed back in.
            wps = self.capacity * self.W
            gidx = np.asarray(gidx, np.int64)
            rows = gidx // wps
            cur = np.fromiter(
                (self._slot_epoch.get(s, 0) for s in slots),
                np.int64, len(slots))
            keep = cur[rows] == np.asarray(epochs, np.int64)[rows]
            if self._mirror_stale:
                # a re-subscribed slot's stream must not XOR onto its
                # stale mirror base; the row refreshes from device on
                # the next peek instead
                stale = np.fromiter(
                    (s in self._mirror_stale for s in slots),
                    bool, len(slots))
                keep &= ~stale[rows]
            g, v = (gidx, chg_vals) if keep.all() else (gidx[keep],
                                                        chg_vals[keep])
            srows = np.asarray(slots, np.int64)[g // wps]
            self._mirror.reshape(self.s_max, wps)[srows, g % wps] ^= v
        self._apply_deferred_mirror_ops()

    def _mirror_xor_triples(self, slots, epochs, tri) -> None:  # gwlint: allow[host-sync] -- harvest-phase mirror upkeep on already-fetched host arrays
        """Apply a tick's triples to the host mirror.  Each triple flips one
        unique (row, bit), so a scatter-XOR of single-bit masks applies the
        tick exactly; the epoch/stale guards mirror _mirror_xor_stream."""
        c = self.capacity
        obs = tri[:, 0].astype(np.int64)
        rows = obs // c
        cur = np.fromiter(
            (self._slot_epoch.get(s, 0) for s in slots),
            np.int64, len(slots))
        keep = cur[rows] == np.asarray(epochs, np.int64)[rows]
        if self._mirror_stale:
            stale = np.fromiter(
                (s in self._mirror_stale for s in slots),
                bool, len(slots))
            keep &= ~stale[rows]
        if not keep.all():
            obs, rows, tri = obs[keep], rows[keep], tri[keep]
        j = tri[:, 1].astype(np.int64)
        srows = np.asarray(slots, np.int64)[rows]
        # planar layout: column j lives at word j % W, bit j // W
        gw = (srows * c + obs % c) * self.W + j % self.W
        bit = (j // self.W).astype(np.uint32)
        np.bitwise_xor.at(self._mirror.reshape(-1), gw, np.uint32(1) << bit)

    def _grow_pool(self, nw: int, bw: int, full: bool = False) -> None:
        """Spill re-arm (the growth half of the _PageDecay contract,
        mirroring the tri/chunk cap growth): double the pool, bounded by
        pool_ceiling -- a pool at the ceiling can NEVER spill (full word
        coverage plus per-bin rounding) -- and reinitialize the free list
        at the next dispatch.  ``full`` jumps straight to the ceiling: a
        WHOLE-TICK spill (> MAX_SPILL bins) is an unambiguous undersize
        signal, and doubling through a sustained storm would spill every
        tick of it; _PageDecay shrinks the pool back afterwards."""
        ceil_p = PG.pool_ceiling(nw, bw)
        grown = ceil_p if full else min(ceil_p, max(self._n_pages * 2, 64))
        if grown > self._n_pages:
            self._n_pages = grown
            self._page_free = None
        if self._pages is not None:
            self._pages.reset_after_growth()

    def _harvest_paged(self, rec) -> None:  # gwlint: allow[host-sync] -- paged-path drain point: fetches the used page prefix once per flush
        """Harvest one paged tick: fetch the used page prefix + page table
        + scalars, validate the allocator's page table, merge any spilled
        bins' words re-read from the kept change grid, XOR the mirror, and
        publish (docs/perf.md paged storage; docs/robustness.md spill
        chain).  Degradation ladder: spilled bins re-read host-side
        (counted in page_spills, same-tick bit-exact); pool exhaustion
        injected through the ``aoi.pages`` seam (oom/fail/partial) forces
        a counted whole-tick spill from the raw grids and re-arms the
        pool; a corrupt page table (``aoi.pages`` poison, or real
        allocator rot) re-raises as RESOURCE_EXHAUSTED to ride
        :meth:`_recover_harvest`'s rebuild-from-host-shadows."""
        slots, s_n = rec["slots"], rec["s_n"]
        n_pages, bw = rec["n_pages"], rec["bin_words"]
        c = self.capacity
        (new, chg, pg, pc, pn) = rec["scratch"]
        nw = s_n * c * self.W
        faults.check("aoi.fetch")  # stallable: a delayed host sync
        t_f0 = time.perf_counter()
        _tf = _T.t()
        poisoned = False
        n_used = n_spill = 0
        page_spec = page_fault = None
        bun_h = None
        if not rec.get("all_unsub"):
            if rec.get("bundle") is not None:
                # fused tick: scalars + page_tab + spill_bins ride ONE
                # int32 bundle -- a single blocking fetch replaces the
                # unfused path's three (ops/aoi_fused)
                bun_h = np.asarray(rec["bundle"])
                raw = faults.filter("aoi.scalars", bun_h[:4])
            else:
                raw = faults.filter("aoi.scalars",
                                    np.asarray(rec["scalars"]))
            n_used, n_spill, nz_fit, nz_total = (int(v) for v in raw)
            n_bins = -(-nw // bw)
            if not (0 <= n_used <= n_pages and 0 <= n_spill <= n_bins
                    and 0 <= nz_fit <= nw and 0 <= nz_total <= nw):
                from ..utils import gwlog

                self.stats["poisoned"] += 1
                gwlog.logger("gw.aoi").warning(
                    "AOI page scalars failed validation (used=%d spill=%d "
                    "fit=%d total=%d); recovering the tick from the raw "
                    "diff grids", n_used, n_spill, nz_fit, nz_total)
                poisoned = True
                n_used = n_spill = 0
            # the aoi.pages seam (docs/robustness.md): oom/fail = pool
            # exhaustion, partial = untrustworthy allocation -- all three
            # force the counted whole-tick spill below; poison corrupts
            # the fetched page table (validated further down)
            try:
                page_spec = faults.check("aoi.pages")
            except Exception as pe:
                if not _device_fault(pe):
                    raise
                page_fault = pe
            if page_spec is not None and page_spec.kind == "partial":
                page_fault = page_spec
        shrink = (None if poisoned or n_spill or page_fault is not None
                  else self._pages.observe(n_used, n_pages))
        if shrink is not None and shrink < self._n_pages:
            self._n_pages = shrink
            self._page_free = None  # reinit at the shrunk size
        if poisoned or page_fault is not None or n_spill > PG.MAX_SPILL:
            # whole-tick spill: the page stream is untrustworthy (poisoned
            # scalars), the allocator faulted (aoi.pages oom/fail/partial),
            # or more bins spilled than the reporting vector holds --
            # recover this tick from the raw diff grids riding the same
            # record (bit-exact; np.nonzero's ascending flat order matches
            # the device extraction's), then re-arm the pool
            if not poisoned:
                from ..utils import gwlog

                self.stats["page_spills"] += 1
                gwlog.logger("gw.aoi").warning(
                    "AOI page pool unusable this tick (%s); spilling the "
                    "whole tick to host and re-arming the pool",
                    page_fault if page_fault is not None
                    else f"{n_spill} bins spilled > {PG.MAX_SPILL}")
                # organic mass-spill = the pool is way undersized: jump to
                # the ceiling.  A fault-caused spill says nothing about
                # size, so it only doubles.
                self._grow_pool(nw, bw, full=page_fault is None)
            chg_h = np.asarray(chg).reshape(-1)
            new_h = np.asarray(new).reshape(-1)
            gidx = np.nonzero(chg_h)[0]
            chg_vals = chg_h[gidx]
            ent_vals = chg_vals & new_h[gidx]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
            t_f0 = time.perf_counter()
            _td = _T.t()
            self._mirror_xor_stream(slots, rec["epochs"], gidx, chg_vals)
            self._scratch.setdefault(rec["key"], rec["scratch"])
            self._publish(slots, rec["epochs"], chg_vals, ent_vals, gidx,
                          s_n)
            self.perf["decode_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.diff", _td)
            return
        if n_used == 0:
            pg_h = np.empty((0, PG.PAGE_WORDS), np.int32)
            pc_h = pn_h = np.empty((0, PG.PAGE_WORDS), np.uint32)
        else:
            pf = rec["prefetch"]
            if pf is not None and pf[0] >= n_used:
                pg_h, pc_h, pn_h = (np.asarray(a)[:n_used] for a in pf[1])
            else:
                ndp = min(n_pages, -(-max(n_used, 1) // 16) * 16)
                slices = (pg[:ndp], pc[:ndp], pn[:ndp])
                for a in slices:
                    a.copy_to_host_async()
                pg_h, pc_h, pn_h = (np.asarray(a)[:n_used] for a in slices)
        self.perf["fetch_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.fetch", _tf)
        # refit the next dispatch's optimistic page prefetch to this tick
        self._pred_pages = max(
            64, min(self._n_pages, -(-n_used * 5 // 4 // 16) * 16))
        t_f0 = time.perf_counter()
        _tp = _T.t()
        if n_used:
            # page-table integrity: the table is the allocator's word of
            # which logical pages back this tick; a duplicate, out-of-range
            # or truncated id means the free list itself is corrupt -- not
            # a per-tick cap problem -- so the ONLY safe recovery is the
            # full device-state rebuild from the host shadows
            tab_h = (bun_h[4:4 + n_pages] if bun_h is not None
                     else np.asarray(rec["page_tab"]))
            if page_spec is not None and page_spec.kind == "poison":
                tab_h = np.full_like(tab_h, np.iinfo(np.int32).min)
            if not PG.validate_page_table(tab_h, n_used, n_pages):
                self.stats["poisoned"] += 1
                self._page_free = None  # rebuilt (arange) at next dispatch
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: aoi.pages page table failed "
                    f"validation (n_used={n_used}, n_pages={n_pages}) -- "
                    "allocator free list corrupt")
        gidx, chg_vals, new_vals = PG.decode_pages(pg_h, pc_h, pn_h)
        gidx = gidx.astype(np.int64)
        if n_spill:
            # counted graceful degradation: the pool served every bin it
            # could; the spilled bins' words are re-read from the kept
            # change grid (small per-bin D2H slices), merged unsorted --
            # the mirror XOR is order-independent over unique words and
            # both emit paths sort before expansion -- and the pool grows
            # for the next tick (decay shrinks it back post-storm)
            self.stats["page_spills"] += n_spill
            sb = (bun_h[4 + n_pages:] if bun_h is not None
                  else np.asarray(rec["spill_bins"]))
            sg, sc, sn2 = PG.spill_stream(chg.reshape(-1), new.reshape(-1),
                                          sb, bw, nw)
            gidx = np.concatenate([gidx, sg])
            chg_vals = np.concatenate([chg_vals, sc])
            new_vals = np.concatenate([new_vals, sn2])
            self._grow_pool(nw, bw)
        ent_vals = chg_vals & new_vals
        self.stats["page_occupancy"] = (n_used / n_pages) if n_pages else 0.0
        _T.lap("aoi.pages", _tp)
        _td = _T.t()
        self._mirror_xor_stream(slots, rec["epochs"], gidx, chg_vals)
        self._scratch.setdefault(rec["key"], rec["scratch"])
        self._publish(slots, rec["epochs"], chg_vals, ent_vals, gidx, s_n)
        self.perf["decode_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.diff", _td)

    def _harvest_tri(self, rec) -> None:  # gwlint: allow[host-sync] -- triples-path drain point: fetches the compact triple buffer once per flush
        """Harvest one tri-mode tick: fetch the compact (observer, observed,
        kind) triples + count scalar, XOR the mirror, and fan the pairs out
        through the native/vector emit layer (docs/perf.md emit paths)."""
        slots, s_n, mt = rec["slots"], rec["s_n"], rec["mt"]
        c = self.capacity
        (new, chg, tri) = rec["scratch"]
        faults.check("aoi.fetch")  # stallable: a delayed host sync
        t_f0 = time.perf_counter()
        _tf = _T.t()
        poisoned = False
        if rec.get("all_unsub"):
            count = 0
        else:
            raw = faults.filter("aoi.scalars", np.asarray(rec["scalars"]))
            count = int(raw[0])
            if not 0 <= count <= s_n * c * c:
                from ..utils import gwlog

                self.stats["poisoned"] += 1
                gwlog.logger("gw.aoi").warning(
                    "AOI triple count failed validation (count=%d); "
                    "recovering the tick from the raw diff grids", count)
                poisoned = True
        shrink = (None if poisoned or count > mt else
                  self._tri.observe(count, self._max_triples))
        if shrink is not None:
            self._max_triples = shrink
        if poisoned or count > mt:
            # triple-capacity overflow (or corrupt count): the compact
            # buffer is truncated, so recover this tick from the raw diff
            # grids riding the same record, then grow the cap so the next
            # tick compacts on device again (counted, never silent --
            # docs/robustness.md)
            if not poisoned:
                self.stats["decode_overflow"] += 1
                if self._max_triples < _TRI_MAX:
                    self._max_triples = min(
                        _TRI_MAX, 1 << (2 * count - 1).bit_length())
                self._tri.reset_after_growth()
            chg_h = np.asarray(chg).reshape(-1)
            new_h = np.asarray(new).reshape(-1)
            gidx = np.nonzero(chg_h)[0]
            chg_vals = chg_h[gidx]
            ent_vals = chg_vals & new_h[gidx]
            self.perf["fetch_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.fetch", _tf)
            t_f0 = time.perf_counter()
            _td = _T.t()
            self._mirror_xor_stream(slots, rec["epochs"], gidx, chg_vals)
            self._scratch.setdefault(rec["key"], rec["scratch"])
            self._publish(slots, rec["epochs"], chg_vals, ent_vals, gidx,
                          s_n)
            self.perf["decode_s"] += time.perf_counter() - t_f0
            _T.lap("aoi.diff", _td)
            return
        if count == 0:
            tri_h = np.empty((0, 3), np.int32)
        else:
            pf = rec["prefetch"]
            if pf is not None and pf[0] >= count:
                tri_h = np.asarray(pf[1])[:count]
            else:
                ndp = min(mt, -(-count // 256) * 256)
                sl_tri = tri[:ndp]
                sl_tri.copy_to_host_async()
                tri_h = np.asarray(sl_tri)[:count]
        self.perf["fetch_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.fetch", _tf)
        # refit the next dispatch's optimistic prefetch to this tick
        self._pred_tri = max(
            2048, min(self._max_triples, -(-count * 5 // 4 // 256) * 256))
        t_f0 = time.perf_counter()
        _td = _T.t()
        if self._mirror is not None:
            if len(tri_h):
                self._mirror_xor_triples(slots, rec["epochs"], tri_h)
            self._apply_deferred_mirror_ops()
        self._scratch.setdefault(rec["key"], rec["scratch"])
        self.perf["decode_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.decode", _td)
        t_f0 = time.perf_counter()
        _te = _T.t()
        try:
            faults.check("aoi.emit")
            pe, pl = AE.fanout_triples(tri_h, c,
                                       native=(self._emit == "native"))
        except Exception as e:
            if not (_device_fault(e) or isinstance(e, RuntimeError)):
                raise
            # emit seam tripped (or the native layer rejected the buffer):
            # demote sticky to host decode and publish this tick through
            # the oracle path -- bit-exact, mirror untouched (_publish
            # never XORs)
            _demote_emit(self, e)
            chg_vals, ent_vals, gidx = EV.triples_to_words(tri_h, c)
            self._publish(slots, rec["epochs"], chg_vals, ent_vals, gidx,
                          s_n)
        else:
            self._publish_pairs(slots, rec["epochs"], _split_rows(pe),
                                _split_rows(pl))
        self.perf["emit_s"] += time.perf_counter() - t_f0
        _T.lap("aoi.emit", _te)

    def _apply_deferred_mirror_ops(self) -> None:
        """Clears issued after a tick's dispatch apply now, AFTER its
        stream (see _mirror_apply).  Applied directly: the NEXT tick may
        already be in flight, and re-deferring would postpone them forever.
        The epoch tag drops ops whose slot was released since queueing -- a
        reacquired slot may carry freshly seeded words (set_prev) the dead
        occupant's clear must not touch."""
        if not self._mirror_ops:
            return
        ops, self._mirror_ops = self._mirror_ops, []
        for op in ops:
            if self._slot_epoch.get(op[1], 0) == op[-1]:
                self._mirror_apply_now(op[:-1])

    def _publish(self, slots, epochs, chg_vals, ent_vals, gidx,
                 s_n: int) -> None:
        """Expand a classified change stream into per-slot (enter, leave)
        pair arrays and merge them into the deliverable events (shared by
        the device harvest and the host-recovery tick).  The expansion runs
        through the bucket's emit path (native C++ when emit="native", host
        numpy otherwise) -- identical order either way."""
        pe, pl = _emit_expand(self, chg_vals, ent_vals, gidx, s_n)
        self._publish_pairs(slots, epochs, _split_rows(pe), _split_rows(pl))

    def _publish_pairs(self, slots, epochs, ent_rows, lv_rows) -> None:
        """Merge per-space-row (enter, leave) pair dicts into the
        deliverable events, under the slot-epoch liveness guard."""
        empty = np.empty((0, 2), np.int32)
        for row, (slot, epoch) in enumerate(zip(slots, epochs)):
            if self._slot_epoch.get(slot, 0) != epoch:
                # slot released (and possibly reused) since this tick was
                # dispatched: its events belong to a dead space
                continue
            e = ent_rows.get(row, empty)
            l = lv_rows.get(row, empty)
            pend = self._events.get(slot)
            if pend is not None:
                # a mid-dispatch harvest (grow_space inside an AOI hook
                # calls get_prev -> flush) can land while another space's
                # prior-tick events are still undelivered: APPEND, never
                # clobber -- replay order stays oldest-first
                e = np.concatenate([pend[0], e])
                l = np.concatenate([pend[1], l])
            self._events[slot] = (e, l)

    def release_slot(self, slot: int) -> None:
        self._slot_epoch[slot] = self._slot_epoch.get(slot, 0) + 1
        super().release_slot(slot)

    def clear_entity(self, slot: int, entity_slot: int) -> None:
        self._pending_clear.append((slot, entity_slot))
        self._mirror_apply(("clear", slot, entity_slot))

    def _mirror_apply(self, op: tuple) -> None:
        """Apply (or defer) one mirror maintenance op.  With a tick in
        flight the op postdates that tick's stream, so it queues (tagged
        with the slot's current epoch) and runs after the harvest XOR;
        otherwise it applies immediately so derivations before the next
        flush already see it."""
        if self._mirror is None:
            return
        if self._inflight is not None:
            self._mirror_ops.append(op + (self._slot_epoch.get(op[1], 0),))
            return
        self._mirror_apply_now(op)

    def _mirror_apply_now(self, op: tuple) -> None:
        if op[0] == "reset":
            self._mirror[op[1]] = 0
        else:
            _slot, e = op[1], op[2]
            self._mirror[_slot, e, :] = 0
            w, b = P.word_bit_for_column(e, self.capacity)
            self._mirror[_slot, :, w] &= np.uint32(
                ~(np.uint32(1) << np.uint32(b)) & 0xFFFFFFFF)

    def _stage_inputs(self, sl, old_x, old_z, old_r, old_act) -> None:
        """Bring the device-resident staged inputs up to date with the host
        shadow.  The steady path ships a sparse (row, col, x, z) packet
        applied by a donated scatter (ops/aoi_stage.py); the fallbacks ship
        full role arrays through _h2d: after grow/reset, when r/act/sub
        changed, when the changed fraction exceeds _delta_max_frac, or when
        delta staging is disabled (the bench's full-restage baseline).

        The diff compares float BIT PATTERNS: device copies must stay
        byte-identical to the shadow or delta-staged ticks would diverge
        from full-staged ones (the bit-exactness contract)."""
        from ..ops import aoi_stage as AS

        new_x, new_z = self._hx[sl], self._hz[sl]
        diff = (new_x.view(np.uint32) != old_x.view(np.uint32)) \
            | (new_z.view(np.uint32) != old_z.view(np.uint32))
        n_changed = np.count_nonzero(diff)  # host numpy scalar
        if not (np.array_equal(self._hr[sl], old_r)
                and np.array_equal(self._hact[sl], old_act)):
            self._dev_stale.add("ra")
            self._dev_stale.add("xz")  # r/act change: full-restage fallback
        stale = self._dev_stale
        if (self.delta_staging and not stale
                and n_changed <= self._delta_max_frac * diff.size):
            if n_changed:
                faults.check("aoi.delta")
                rows, cols = np.nonzero(diff)
                pkt = AS.pad_packet(sl[rows], cols, new_x[rows, cols],
                                    new_z[rows, cols],
                                    page_granular=self.paged)
                self._dev["x"], self._dev["z"] = AS.apply_packet(
                    self._dev["x"], self._dev["z"], *pkt)
                self.stats["h2d_bytes"] += AS.packet_nbytes(*pkt)
            self.stats["delta_flushes"] += 1
            return
        if (not self.delta_staging or "xz" in stale or n_changed
                or "x" not in self._dev):
            self._dev["x"] = self._h2d("x", self._hx)
            self._dev["z"] = self._h2d("z", self._hz)
        if "ra" in stale or "r" not in self._dev:
            self._dev["r"] = self._h2d("r", self._hr)
            self._dev["act"] = self._h2d("act", self._hact)
        if "sub" in stale or "sub" not in self._dev:
            self._dev["sub"] = self._h2d("sub", self._hsub)
        stale.clear()
        self.stats["full_flushes"] += 1

    def _h2d(self, role: str, arr: np.ndarray):
        """Full upload of one shadow-backed role array -- THE seam every
        full-array staged-input H2D rides (gwlint h2d-staging); its sparse
        sibling is the delta packet in _stage_inputs."""
        import jax.numpy as jnp

        faults.check("aoi.h2d")
        self.stats["h2d_bytes"] += arr.nbytes
        return jnp.asarray(arr)

    def get_prev(self, slot: int) -> np.ndarray:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        self.flush()  # apply pending resets/steps before reading
        if self.prev is None:  # device down: the mirror IS the state
            self._ensure_mirror()
            return np.array(self._mirror[slot], copy=True)
        return np.asarray(self.prev[slot])

    def set_prev(self, slot: int, words: np.ndarray) -> None:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        self.flush()
        self._pending_reset.discard(slot)
        w = np.asarray(words, np.uint32)
        if self.prev is not None:
            self.prev = self.prev.at[slot].set(self._jnp.asarray(w))
        else:  # device down: seed the durable copy; rebuild uploads it
            self._ensure_mirror()
        self._mirror_stale.discard(slot)  # mirror row set to truth below
        if self._mirror is not None:
            self._mirror[slot] = w

    # -- live migration & chip-loss failover (docs/robustness.md) --------

    def _mark_evacuating(self) -> None:
        """The device is LOST (faults.DeviceLost): never touch it again.
        Host-oracle mode (calc level 2) keeps the bucket serving bit-exact
        ticks from (mirror, shadows) until the engine rebuilds its spaces
        onto a fresh bucket at the end of the current flush."""
        self._evacuating = True
        self._calc_level = 2
        self.stats["calc_level"] = 2
        self._need_rebuild = False  # there is no device to rebuild onto

    def export_snapshot(self, slot: int) -> dict:  # gwlint: allow[host-sync] -- migration snapshot, off the steady tick path
        """Live-migration wire image of one slot: the input shadows as a
        delta-staging packet + the previous-tick interest words.  Drains
        any pipelined in-flight tick first so the delivered event stream
        and the snapshot agree (double-cover alignment)."""
        self.drain()
        return _build_snapshot(
            self.capacity, self._hx[slot], self._hz[slot], self._hr[slot],
            self._hact[slot], bool(self._hsub[slot]), self.get_prev(slot))

    def import_snapshot(self, slot: int, snap: dict) -> None:  # gwlint: allow[host-sync] -- migration replay, off the steady tick path
        """Replay a migration snapshot onto this slot: scatter the packet
        into the input shadows (device copies invalidated -> the next
        flush full-restages) and seed prev from the words.  Bit-exact with
        the source tier: shadows are the durable truth everywhere (the
        delta-staging contract)."""
        if snap["capacity"] != self.capacity:
            raise ValueError(
                f"snapshot capacity {snap['capacity']} != bucket "
                f"capacity {self.capacity}")
        x, z = _unpack_positions(snap)
        self._hx[slot] = x
        self._hz[slot] = z
        self._hr[slot] = snap["r"]
        self._hact[slot] = snap["act"]
        self.set_subscribed(slot, snap["sub"])
        self._dev_stale.update(("xz", "ra", "sub"))
        self.set_prev(slot, snap["words"])

    def evacuate(self) -> dict[int, dict]:
        """Snapshot every occupied slot for rebuild on a surviving device
        (the engine drives this after a DeviceLost recovery marked the
        bucket evacuating)."""
        live = sorted(set(range(self.n_slots)) - set(self._free))
        return {slot: self.export_snapshot(slot) for slot in live}

