"""Entity RPC exposure -- declarative, no reflection-by-naming.

The reference encodes who may call a method in its *name suffix* (``Foo``
server-only, ``Foo_Client`` own client, ``Foo_AllClients`` any client --
/root/reference/engine/entity/rpc_desc.go:8-46, enforced at
Entity.go:499-512).  Name-suffix reflection is a Go-ism; here exposure is
declared with a decorator and collected at registration time into a per-type
descriptor table:

    class Avatar(Entity):
        @rpc(expose=OWN_CLIENT)
        def say(self, text: str): ...

Exposure levels:
  * SERVER      -- only other server entities may call (the default);
  * OWN_CLIENT  -- the entity's own client may call (reference ``_Client``);
  * ALL_CLIENTS -- any client may call (reference ``_AllClients``).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Any, Callable

SERVER = "server"
OWN_CLIENT = "own_client"
ALL_CLIENTS = "all_clients"

_EXPOSURES = (SERVER, OWN_CLIENT, ALL_CLIENTS)
_MARK = "_gw_rpc_expose"


def rpc(fn: Callable | None = None, *, expose: str = SERVER):
    """Mark an entity method as remotely callable."""
    if expose not in _EXPOSURES:
        raise ValueError(f"unknown exposure {expose!r}")

    def deco(f):
        setattr(f, _MARK, expose)
        return f

    return deco(fn) if fn is not None else deco


@dataclass(frozen=True)
class RpcDesc:
    name: str
    expose: str
    func: Callable
    min_args: int  # required positional arity excluding self
    max_args: int | None  # None = *args (unbounded)

    def arity_ok(self, n: int) -> bool:
        if n < self.min_args:
            return False
        return self.max_args is None or n <= self.max_args


def collect_rpc_descs(cls: type) -> dict[str, RpcDesc]:
    """Walk a class (MRO-aware) and build its RPC descriptor table."""
    descs: dict[str, RpcDesc] = {}
    for name in dir(cls):
        if name.startswith("_"):
            continue
        fn = getattr(cls, name, None)
        expose = getattr(fn, _MARK, None)
        if expose is None or not callable(fn):
            continue
        min_args, max_args = 0, 0
        try:
            for p in list(inspect.signature(fn).parameters.values())[1:]:  # skip self
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                    if max_args is not None:
                        max_args += 1
                    if p.default is p.empty:
                        min_args += 1
                elif p.kind == p.VAR_POSITIONAL:
                    max_args = None
        except (TypeError, ValueError):
            min_args, max_args = 0, None
        descs[name] = RpcDesc(name, expose, fn, min_args, max_args)
    return descs


def may_call(desc: RpcDesc, *, from_client: bool, is_owner: bool) -> bool:
    """Access check mirroring the reference's flag test (Entity.go:499-512)."""
    if not from_client:
        return True
    if desc.expose == ALL_CLIENTS:
        return True
    if desc.expose == OWN_CLIENT:
        return is_owner
    return False
