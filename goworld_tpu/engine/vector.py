"""3-vector used for entity positions (reference:
/root/reference/engine/entity/Vector3.go).  AOI operates on the X-Z plane.

Hot-path note: one Vector3 is constructed per set_position per entity per
tick, so this is a plain ``__slots__`` class -- the earlier frozen-dataclass
version (3 ``object.__setattr__`` + 3 float32 casts) cost ~1.2 us per
construction and dominated the engine tick's host time.  Components are
plain floats; float32 quantization happens where it matters bit-for-bit, at
the AOI array boundary (Space's packed f32 arrays)."""

from __future__ import annotations

import math


class Vector3:
    __slots__ = ("x", "y", "z")

    def __init__(self, x: float = 0.0, y: float = 0.0, z: float = 0.0):
        self.x = float(x)
        self.y = float(y)
        self.z = float(z)

    def __repr__(self) -> str:
        return f"Vector3({self.x}, {self.y}, {self.z})"

    def __eq__(self, o) -> bool:
        return (isinstance(o, Vector3) and self.x == o.x and self.y == o.y
                and self.z == o.z)

    def __hash__(self) -> int:
        return hash((self.x, self.y, self.z))

    def distance_to(self, o: "Vector3") -> float:
        return math.sqrt(
            (self.x - o.x) ** 2 + (self.y - o.y) ** 2 + (self.z - o.z) ** 2
        )

    def add(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x + o.x, self.y + o.y, self.z + o.z)

    def sub(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x - o.x, self.y - o.y, self.z - o.z)

    def scale(self, s: float) -> "Vector3":
        return Vector3(self.x * s, self.y * s, self.z * s)

    def normalized(self) -> "Vector3":
        d = math.sqrt(self.x**2 + self.y**2 + self.z**2)
        if d == 0:
            return Vector3()
        return self.scale(1.0 / d)

    def dir_to_yaw(self) -> float:
        """Yaw (degrees) of this direction on the X-Z plane."""
        return math.degrees(math.atan2(self.x, self.z))

    def to_tuple(self):
        return (self.x, self.y, self.z)
