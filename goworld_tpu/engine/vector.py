"""float32 3-vector used for entity positions (reference:
/root/reference/engine/entity/Vector3.go).  AOI operates on the X-Z plane."""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

_f32 = np.float32


@dataclass(frozen=True)
class Vector3:
    x: float = 0.0
    y: float = 0.0
    z: float = 0.0

    def __post_init__(self):
        object.__setattr__(self, "x", float(_f32(self.x)))
        object.__setattr__(self, "y", float(_f32(self.y)))
        object.__setattr__(self, "z", float(_f32(self.z)))

    def distance_to(self, o: "Vector3") -> float:
        return math.sqrt(
            (self.x - o.x) ** 2 + (self.y - o.y) ** 2 + (self.z - o.z) ** 2
        )

    def add(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x + o.x, self.y + o.y, self.z + o.z)

    def sub(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x - o.x, self.y - o.y, self.z - o.z)

    def scale(self, s: float) -> "Vector3":
        return Vector3(self.x * s, self.y * s, self.z * s)

    def normalized(self) -> "Vector3":
        d = math.sqrt(self.x**2 + self.y**2 + self.z**2)
        if d == 0:
            return Vector3()
        return self.scale(1.0 / d)

    def dir_to_yaw(self) -> float:
        """Yaw (degrees) of this direction on the X-Z plane."""
        return math.degrees(math.atan2(self.x, self.z))

    def to_tuple(self):
        return (self.x, self.y, self.z)
