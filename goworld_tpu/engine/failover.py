"""Kill-a-host failover driver: SIGKILL a real game process, lose nothing.

The cluster-supervision proof (docs/robustness.md "Cluster supervision &
host failover"), built like the crash-restart driver in
engine/checkpoint.py but one level up: instead of one process SIGKILLing
itself, a real DispatcherService (leases armed) supervises two child GAME
WORKER processes, and the parent kills one of them mid-traffic with a
genuine ``kill -9``.

Worker (``python -m goworld_tpu.engine.failover --worker ...``): a raw
wire client owning one space.  It registers its slot eids over
MT_SET_GAME_ID, renews its lease after every applied batch, applies each
regrouped MT_SYNC_POSITION_YAW_FROM_CLIENT batch as one engine tick
(the tick stamp rides the records' unused y field), journals one line
per tick ("<tick> <crc:08x> <n_events>", line-buffered -- the
delivered-event record a SIGKILL cannot retract) and streams continuous
checkpoints into the SHARED checkpoint store.  On MT_REHOME_SPACES it
adopts a dead peer's spaces via CheckpointController.restore_into; on
MT_REPLAY_MOVES it re-applies the dispatcher-buffered batches, deduping
by stamp against the restored checkpoint tick.

Parent (:func:`host_failover_scenario`): in-process dispatcher + a raw
gate link driving deterministic per-(tick, slot) movement for both
spaces, a poll-then-SIGKILL of worker 1 once its journal reaches
``kill_at`` (crossing the ``clu.kill`` seam first), and the merge: the
dead worker's journal plus the survivor's post-restore journal must be
CRC-equal, tick for tick, to an unkilled in-process oracle --
events_lost == 0 is the acceptance bar, ticks_to_recover the cost.

Shared by the ``engine_failover_host`` bench row,
scripts/host_failover_smoke.py (CI) and the ``soak_host_failover``
round in scripts/faults_soak.py.
"""

from __future__ import annotations

import os
import signal
import struct
import subprocess
import sys
import threading
import time

import numpy as np

from .. import faults
from ..netutil import Packet, PacketConnection, connect_tcp
from .. import telemetry
from ..telemetry import flight, tracectx
from ..proto import GWConnection, msgtypes as MT
from .checkpoint import (CheckpointController, _open_backends,
                         _read_journal, _tick_crc, _walk_frames)
from .ids import fixed_id

_REC = struct.Struct("<4f")  # x, y (tick stamp), z, yaw


def _space_eids(space_id: str, cap: int) -> list[str]:
    """Deterministic slot eids -- parent and workers compute identically
    (slot i of space S is always fixed_id("S:i"))."""
    return [fixed_id(f"{space_id}:{i}") for i in range(cap)]


# -- worker: one real game process ------------------------------------------


class _WorkerSpace:
    """One owned space: engine handle + the full position arrays each
    applied batch overwrites (records cover every slot, so the arrays
    never need restoring -- only the bucket's interest state does).
    ``ctl`` is the checkpoint controller journaling this space: the
    worker's own for native spaces, the dead game's re-opened namespace
    for adopted ones (the chain must stay monotonic where it lives)."""

    def __init__(self, handle, ctl, space_id: str, cap: int,
                 journal_dir: str, last_tick: int):
        self.h = handle
        self.ctl = ctl
        self.id = space_id
        self.slot = {eid: i for i, eid in enumerate(_space_eids(space_id, cap))}
        self.x = np.zeros(cap, np.float32)
        self.z = np.zeros(cap, np.float32)
        self.r = np.full(cap, 100.0, np.float32)
        self.act = np.ones(cap, bool)
        self.last = last_tick  # highest applied tick stamp (dedup fence)
        self.jf = open(os.path.join(journal_dir, f"{space_id}.journal"),
                       "a", buffering=1)


class _Worker:
    def __init__(self, args):
        from .aoi import AOIEngine

        self.args = args
        self.eng = AOIEngine("cpu")
        # per-game namespace under the SHARED checkpoint root: each game
        # writes its own manifest log (no cross-process append races); a
        # survivor restores by re-opening the dead game's namespace fresh
        store, kv = _open_backends(
            os.path.join(args.ckpt_dir, f"game{args.game_id}"))
        self.ctl = CheckpointController(self.eng, store, kv,
                                        mode="continuous", interval=4)
        self.spaces: dict[str, _WorkerSpace] = {}
        h = self.eng._create_handle(args.cap, args.tier)
        self.ctl.track(args.space, h)
        self.spaces[args.space] = _WorkerSpace(
            h, self.ctl, args.space, args.cap, args.journal_dir, 0)
        self.epoch: int | None = None
        self.conn = GWConnection(PacketConnection(
            connect_tcp((args.host, args.port), timeout=10.0)))
        self.conn.send_set_game_id(
            args.game_id, False,
            [eid for sp in self.spaces.values() for eid in sp.slot])
        self.conn.flush()

    def run(self) -> int:
        args = self.args
        while True:
            pkt = self.conn.recv_packet()
            if pkt is None:
                return 1  # dispatcher gone
            # clu.zombie: a stall here parks the whole packet loop -- the
            # lease lapses, our spaces fail over, and everything we send
            # after resuming is fenced (the split-brain probe)
            faults.check("clu.zombie")
            rc = self._handle(pkt)
            if rc is not None:
                return rc
            if all(sp.last >= args.ticks for sp in self.spaces.values()):
                for sp in self.spaces.values():
                    sp.ctl.close()
                return 0

    def _handle(self, pkt) -> int | None:
        msgtype = pkt.read_u16()
        if msgtype == MT.MT_SYNC_POSITION_YAW_FROM_CLIENT:
            self._apply_sync(pkt)
            if self.epoch is not None:
                # piggyback the snapshot like the real GameService does,
                # so the parent dispatcher federates this worker's series
                metrics = (telemetry.snapshot()
                           if telemetry.enabled() else None)
                if metrics is None:
                    self.conn.send_game_lease_renew(
                        self.args.game_id, self.epoch, sorted(self.spaces))
                else:
                    self.conn.send_game_lease_renew(
                        self.args.game_id, self.epoch, sorted(self.spaces),
                        metrics=metrics)
                self.conn.flush()
        elif msgtype == MT.MT_GAME_LEASE_GRANT:
            self.epoch = pkt.read_u32()
            pkt.read_f32()  # ttl: renewal here is per-batch, not timed
        elif msgtype == MT.MT_REHOME_SPACES:
            self._rehome(pkt)
        elif msgtype == MT.MT_REPLAY_MOVES:
            pkt.read_u16()  # dead gid
            n = pkt.read_u32()
            for _ in range(n):
                body = Packet(bytearray(pkt.read_varbytes()))
                assert body.read_u16() == MT.MT_SYNC_POSITION_YAW_FROM_CLIENT
                self._apply_sync(body)
        elif msgtype == MT.MT_GAME_SHUTDOWN:
            print("fenced: shutdown notice", file=sys.stderr)
            return 3
        return None  # anything else (deployment ready, srvdis, ...) ignored

    def _apply_sync(self, pkt) -> None:
        """One regrouped batch = one engine tick for each space it names.
        Dedup by stamp: batches at or below a space's last applied tick
        (the replayed prefix the restored checkpoint already covers) are
        dropped -- the exactly-once half of the failover argument."""
        per_space: dict[str, list] = {}
        stamp = 0
        # defensive: the dispatcher re-stamps relayed batches with a trace
        # trailer when telemetry is on; strip it before the stride-32 loop
        tracectx.try_strip(pkt)
        while pkt.remaining() > 0:
            eid = pkt.read_entity_id()
            x, y, z, _yaw = _REC.unpack(pkt.read_bytes(16))
            stamp = int(round(y))
            for sp in self.spaces.values():
                s = sp.slot.get(eid)
                if s is not None:
                    per_space.setdefault(sp.id, []).append((s, x, z))
                    break
        for sid, recs in per_space.items():
            sp = self.spaces[sid]
            if stamp <= sp.last:
                continue
            for s, x, z in recs:
                sp.x[s] = x
                sp.z[s] = z
            self.eng.submit(sp.h, sp.x, sp.z, sp.r, sp.act)
            self.eng.flush()
            e, lv = self.eng.take_events(sp.h)
            crc, n = _tick_crc(e, lv)
            sp.jf.write(f"{stamp} {crc:08x} {n}\n")
            sp.last = stamp
            sp.ctl.capture(sid, stamp)

    def _rehome(self, pkt) -> None:
        dead_gid = pkt.read_u16()
        epoch = pkt.read_u32()
        n = pkt.read_u32()
        # fresh controller over the DEAD game's checkpoint namespace: the
        # filesystem kvdb replays its manifest log at open, so only a
        # fresh open sees everything the dead process landed before the
        # kill.  The adopted spaces keep checkpointing through it -- their
        # manifest chains stay monotonic where they already live.
        store, kv = _open_backends(
            os.path.join(self.args.ckpt_dir, f"game{dead_gid}"))
        ctl = CheckpointController(self.eng, store, kv,
                                   mode="continuous", interval=4)
        for _ in range(n):
            sid = pkt.read_varstr()
            try:
                faults.check("clu.restore")
                res = ctl.restore_into(self.eng, sid, tier=self.args.tier)
            except Exception as e:
                print(f"rehome {sid} failed: {e!r}", file=sys.stderr)
                continue
            if res is None:
                print(f"rehome {sid}: no consistent checkpoint",
                      file=sys.stderr)
                continue
            h, tick, ck_epoch = res
            sp = _WorkerSpace(h, ctl, sid, self.args.cap,
                              self.args.journal_dir, tick)
            self.spaces[sid] = sp
            sp.jf.write(f"# restored epoch={ck_epoch} tick={tick} "
                        f"ownership={epoch}\n")


def _worker_main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="failover game worker (raw wire client)")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--game-id", type=int, required=True)
    ap.add_argument("--space", required=True)
    ap.add_argument("--cap", type=int, default=48)
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--tier", default="cpu", choices=("cpu", "cpp", "tpu"))
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--journal-dir", required=True)
    args = ap.parse_args(argv)
    os.makedirs(args.journal_dir, exist_ok=True)
    # black box beside the shared checkpoint store (GW_FLIGHT_DIR, if the
    # harness set it, already won at import); with GW_FLIGHT_INTERVAL_S the
    # heartbeat is what leaves a post-mortem behind after SIGKILL
    flight.configure(dir=os.path.join(args.ckpt_dir, "flight"),
                     component=f"game{args.game_id}")
    return _Worker(args).run()


# -- parent harness ----------------------------------------------------------


def _oracle_crcs(cap: int, frames) -> tuple[dict, dict]:
    """{tick: crc_hex}, {tick: n_events} of an unkilled in-process run --
    the same submit/flush/take_events sequence the workers execute."""
    from .aoi import AOIEngine

    eng = AOIEngine("cpu")
    h = eng._create_handle(cap, "cpu")
    r = np.full(cap, 100.0, np.float32)
    act = np.ones(cap, bool)
    crcs, counts = {}, {}
    for t, (x, z) in enumerate(frames, start=1):
        eng.submit(h, x, z, r, act)
        eng.flush()
        e, lv = eng.take_events(h)
        crc, n = _tick_crc(e, lv)
        crcs[t] = f"{crc:08x}"
        counts[t] = n
    return crcs, counts


def _poll(pred, timeout: float, interval: float = 0.005) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _journal_or_empty(path: str) -> tuple[dict, dict, int]:
    if not os.path.exists(path):
        return {}, {}, -1
    return _read_journal(path)


def _journal_last_tick(path: str) -> int:
    crcs, _, _ = _journal_or_empty(path)
    return max(crcs) if crcs else -1


def host_failover_scenario(base_dir: str, cap: int = 48,
                           world: float = 200.0, ticks: int = 48,
                           kill_at: int = 24, tier: str = "cpu",
                           lease_ttl_s: float = 2.0, pace_s: float = 0.01,
                           seed: int = 17,
                           worker_env: dict | None = None) -> dict:
    """Parent harness: dispatcher (leases armed) + 2 worker processes +
    a raw gate link; SIGKILL worker 1 at ``kill_at``; assert the merged
    delivered stream is CRC-equal to the unkilled oracle.  Returns the
    parity verdict, recovery stats and the dispatcher's clu.* counters
    (the engine_failover_host bench record's core fields)."""
    from .. import config
    from ..components.dispatcher.service import DispatcherService

    os.makedirs(base_dir, exist_ok=True)
    ck_dir = os.path.join(base_dir, "ckpt")
    j_dirs = {1: os.path.join(base_dir, "j1"), 2: os.path.join(base_dir, "j2")}
    spaces = {1: "w1", 2: "w2"}
    cfg = config.loads(
        "[deployment]\ndispatchers = 1\ngames = 2\ngates = 1\n"
        "[dispatcher1]\nhost = 127.0.0.1\nport = 0\n"
        f"lease_ttl_s = {lease_ttl_s}\n")
    disp = DispatcherService(1, cfg).start()
    host, port = disp.addr
    procs: dict[int, subprocess.Popen] = {}
    gate = None
    try:
        for gid in (1, 2):
            procs[gid] = subprocess.Popen(
                [sys.executable, "-m", "goworld_tpu.engine.failover",
                 "--worker", "--host", host, "--port", str(port),
                 "--game-id", str(gid), "--space", spaces[gid],
                 "--cap", str(cap), "--ticks", str(ticks), "--tier", tier,
                 "--ckpt-dir", ck_dir, "--journal-dir", j_dirs[gid]],
                env={**os.environ, **(worker_env or {})})
        if not _poll(lambda: len(disp.entities) >= 2 * cap, 60.0):
            raise RuntimeError("workers failed to register")
        gate = GWConnection(PacketConnection(
            connect_tcp((host, port), timeout=10.0)))
        gate.send_set_gate_id(1)
        gate.flush()
        # drain dispatcher->gate traffic so backpressure never stalls it
        def _drain_gate():
            try:
                while gate.recv_packet() is not None:
                    pass
            except (OSError, ValueError):
                pass
        threading.Thread(target=_drain_gate, daemon=True).start()

        frames = {gid: _walk_frames(cap, world, ticks, seed + gid)
                  for gid in (1, 2)}
        eids = {gid: _space_eids(spaces[gid], cap) for gid in (1, 2)}
        crash_j = os.path.join(j_dirs[1], "w1.journal")

        killed_tick = -1
        t0_recover = 0.0
        for t in range(1, ticks + 1):
            p = Packet.for_msgtype(MT.MT_SYNC_POSITION_YAW_FROM_CLIENT)
            for gid in (1, 2):
                x, z = frames[gid][t - 1]
                for i, eid in enumerate(eids[gid]):
                    p.append_entity_id(eid)
                    p.append_bytes(_REC.pack(x[i], float(t), z[i], 0.0))
            gate.send(p)
            gate.flush()
            time.sleep(pace_s)
            if killed_tick < 0 and t >= kill_at:
                # let the victim journal (= deliver) through kill_at, so
                # the crash journal provably overlaps the replay window
                _poll(lambda: _journal_last_tick(crash_j) >= kill_at, 30.0)
                faults.check("clu.kill")
                procs[1].send_signal(signal.SIGKILL)
                procs[1].wait(timeout=30)
                killed_tick = _journal_last_tick(crash_j)
                t0_recover = time.perf_counter()
        ok = _poll(lambda: all(
            _journal_last_tick(os.path.join(j_dirs[2], f"{s}.journal"))
            >= ticks for s in spaces.values()), 120.0)
        recover_wall_s = time.perf_counter() - t0_recover
        procs[2].wait(timeout=30)
    finally:
        if gate is not None:
            gate.close()
        for pr in procs.values():
            if pr.poll() is None:
                pr.kill()
        disp.stop()

    results = {"survivor_done": bool(ok), "killed_tick": killed_tick}
    # w1: dead worker's prefix + survivor's post-restore suffix vs oracle
    o_crc, o_n = _oracle_crcs(cap, frames[1])
    c_crc, c_n, _ = _journal_or_empty(crash_j)
    r_crc, r_n, restored_tick = _journal_or_empty(
        os.path.join(j_dirs[2], "w1.journal"))
    overlap = sorted(set(c_crc) & set(r_crc))
    replay_ok = all(c_crc[t] == r_crc[t] for t in overlap)
    merged, merged_n = dict(c_crc), dict(c_n)
    merged.update(r_crc)
    merged_n.update(r_n)
    parity_ok = (replay_ok and set(merged) == set(o_crc)
                 and all(merged[t] == o_crc[t] for t in o_crc))
    # w2: the survivor's own space must be untouched by the failover
    o2_crc, _o2_n = _oracle_crcs(cap, frames[2])
    w2_crc, _, _ = _journal_or_empty(os.path.join(j_dirs[2], "w2.journal"))
    w2_ok = (set(w2_crc) == set(o2_crc)
             and all(w2_crc[t] == o2_crc[t] for t in o2_crc))
    results.update({
        "ticks": ticks,
        "kill_tick": kill_at,
        "restored_tick": restored_tick,
        "ticks_to_recover": (killed_tick - restored_tick
                             if restored_tick >= 0 else -1),
        "recover_wall_s": recover_wall_s,
        "replayed_overlap_ticks": len(overlap),
        "replay_parity_ok": bool(replay_ok),
        "parity_ok": bool(parity_ok),
        "survivor_space_ok": bool(w2_ok),
        "events_lost": int(sum(o_n.values())
                           - sum(merged_n.get(t, 0) for t in o_n)),
        "oracle_events": int(sum(o_n.values())),
        "clu_stats": dict(disp.clu_stats),
    })
    return results


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        sys.exit(_worker_main(sys.argv[1:]))
    import argparse

    ap = argparse.ArgumentParser(description="host-failover scenario")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--kill-at", type=int, default=24)
    ap.add_argument("--cap", type=int, default=48)
    args = ap.parse_args()
    res = host_failover_scenario(args.dir, cap=args.cap, ticks=args.ticks,
                                 kill_at=args.kill_at)
    print(res)
    sys.exit(0 if res["events_lost"] == 0 and res["parity_ok"] else 1)
