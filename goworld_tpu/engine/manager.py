"""Entity type registry + per-process entity manager.

Reference: /root/reference/engine/entity/EntityManager.go (type descriptors
:24-36, registration :151-189, create :229-273, restore :275-335).  Here
type metadata comes from class declarations (no reflection pass): attr
replication classes, AOI flags and persistence are class attributes on the
Entity subclass; RPC exposure comes from decorators (engine/rpc.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

from .entity import Entity
from .ids import gen_id
from .rpc import RpcDesc, collect_rpc_descs
from .vector import Vector3

if TYPE_CHECKING:
    from .runtime import Runtime
    from .space import Space


@dataclass(frozen=True)
class EntityTypeDesc:
    type_name: str
    cls: type
    is_space: bool
    persistent: bool
    use_aoi: bool
    aoi_distance: float
    rpc_descs: dict[str, RpcDesc]
    # True when the type keeps the default (no-op) AOI hooks: event replay
    # for clientless instances is then pure interest-set bookkeeping and
    # rides the batched fast path (Space.dispatch_aoi_events)
    plain_aoi_hooks: bool = True


class EntityManager:
    def __init__(self, runtime: "Runtime"):
        self.runtime = runtime
        self.registry: dict[str, EntityTypeDesc] = {}
        self.entities: dict[str, Entity] = {}
        self.spaces: dict[str, "Space"] = {}
        # per-type live instances (reference: entity lists per type,
        # entity_map.go); O(1) maintenance, used by services reconciliation
        # and type-scoped queries
        self.by_type: dict[str, set[str]] = {}

    # -- registration ------------------------------------------------------
    def register(self, cls: type, type_name: str | None = None) -> EntityTypeDesc:
        from .space import Space

        if not issubclass(cls, Entity):
            raise TypeError(f"{cls} is not an Entity subclass")
        type_name = type_name or cls.__name__
        if type_name in self.registry:
            raise ValueError(f"entity type {type_name!r} already registered")
        desc = EntityTypeDesc(
            type_name=type_name,
            cls=cls,
            is_space=issubclass(cls, Space),
            persistent=bool(cls.persistent),
            use_aoi=bool(cls.use_aoi),
            aoi_distance=float(cls.aoi_distance),
            rpc_descs=collect_rpc_descs(cls),
            plain_aoi_hooks=(
                cls.on_enter_aoi is Entity.on_enter_aoi
                and cls.on_leave_aoi is Entity.on_leave_aoi
            ),
        )
        self.registry[type_name] = desc
        return desc

    # -- creation ----------------------------------------------------------
    def create(
        self,
        type_name: str,
        *,
        space: "Space | None" = None,
        pos: Vector3 | None = None,
        eid: str | None = None,
        attrs: dict | None = None,
    ) -> Entity:
        """Create an entity locally (reference: createEntity,
        EntityManager.go:229-273)."""
        desc = self.registry.get(type_name)
        if desc is None:
            raise KeyError(f"entity type {type_name!r} not registered")
        e = desc.cls()
        e.id = eid or gen_id()
        if e.id in self.entities:
            raise ValueError(f"entity id {e.id} already exists")
        e.type_name = type_name
        e.manager = self
        e.desc = desc
        e._dirty_set = self.runtime._dirty_entities  # stable set object
        e._plain_aoi = desc.plain_aoi_hooks
        if attrs:
            e.attrs.assign(attrs)
        e.on_init()
        self.entities[e.id] = e
        self.by_type.setdefault(type_name, set()).add(e.id)
        if desc.is_space:
            self.spaces[e.id] = e  # type: ignore[assignment]
        cb = getattr(self.runtime, "on_entity_registered", None)
        if cb is not None:
            cb(e)
        e.on_created()
        if space is not None:
            space.enter_entity(e, pos or Vector3())
        return e

    def create_space(self, cls_name: str, kind: int = 1,
                     eid: str | None = None,
                     attrs: dict | None = None) -> "Space":
        sp = self.create(cls_name, eid=eid, attrs=attrs)
        sp.kind = kind  # type: ignore[attr-defined]
        sp.on_space_init()  # type: ignore[attr-defined]
        return sp  # type: ignore[return-value]

    def restore(self, data: dict, client_factory=None) -> Entity:
        """Recreate an entity from migrate/freeze data (reference:
        restoreEntity, EntityManager.go:275-335).  Space re-entry is the
        caller's job (it knows the target space)."""
        e = self.create(
            data["type"], eid=data["id"], attrs=data.get("attrs") or {}
        )
        x, y, z = data.get("pos", (0, 0, 0))
        e.position = Vector3(x, y, z)
        e.yaw = float(data.get("yaw", 0.0))
        e.client_syncing = bool(data.get("client_syncing", False))
        e.restore_timers(data.get("timers") or [])
        cli = data.get("client")
        if cli is not None and client_factory is not None:
            e.client = client_factory(*cli)
            e._recompute_plain()
        e.on_migrate_in()
        return e

    # -- lookup ------------------------------------------------------------
    def get(self, eid: str) -> Entity | None:
        return self.entities.get(eid)

    def call(self, eid: str, method: str, *args):
        """Local-call fast path (reference: EntityManager.go:429-442); remote
        routing via the dispatcher fabric hooks in here once connected."""
        e = self.entities.get(eid)
        if e is None:
            raise KeyError(f"no local entity {eid}")
        return e.call(method, *args)

    def _on_entity_destroyed(self, e: Entity):
        self.entities.pop(e.id, None)
        self.spaces.pop(e.id, None)
        ids = self.by_type.get(e.type_name)
        if ids is not None:
            ids.discard(e.id)
        cb = getattr(self.runtime, "on_entity_unregistered", None)
        if cb is not None:
            cb(e)
