"""Telemetry-driven placement: live space migration between AOI tiers.

ROADMAP item 3's elasticity story: bucket->tier placement used to be static
config, so a hot space could not leave an overloaded chip and a lost chip
took its spaces down until restart.  This module adds both halves:

  * :class:`PlacementController` -- scores each bucket's placement from the
    same per-bucket load counters the telemetry registry exports (flush
    seconds, entity counts, staged H2D bytes) and, in ``auto`` mode, picks
    at most one space per cooldown window to re-home;

  * :class:`_Migration` -- the live-migration state machine
    (docs/robustness.md):

        snapshot -> replay -> double-cover -> swap
                                  |
                                  +-> rollback (zero loss)

    The source slot's host shadows are exported as a delta-staging packet
    (ops/aoi_stage -- PR 2's H2D wire format doubles as the migration
    serialization) and replayed onto the target bucket.  Then both homes
    compute every tick from the same staged inputs while events keep
    publishing from the SOURCE; each flush the two freshly-appended event
    deltas are compared (CRC over the packed pairs + bit-exact array
    compare, cadence-aligned when exactly one side is pipelined).  Once
    enough aligned flushes verify, ownership swaps atomically: the handle
    object the Space holds is re-pointed in place, undelivered events are
    carried so no enter/leave is lost or duplicated and no tick is
    dropped, and the source slot's epoch bump silences any still-in-flight
    source tick.  Any mismatch -- or any fault recovery on the target
    during the cover (a degraded target recomputes bit-exactly, so CRC
    alone cannot catch it) -- rolls back to the source with zero loss.

The chip-loss failover path (``aoi.device`` fault seam, kind ``reset``)
reuses the same snapshot/import machinery: see AOIEngine._evacuate_bucket.

Cadence rule: during a cover the space's events must be consumed every
tick (the runtime's normal take_events cadence); migrating between a
pipelined and an unpipelined tier shifts delivery by the one documented
pipeline tick, never losing or duplicating events.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass

import numpy as np

from ..telemetry import trace as _T

__all__ = ["PlacementController", "CohortPlanner", "LoadSample",
           "MigrationError"]

_EMPTY = np.empty((0, 2), np.int32)


class MigrationError(RuntimeError):
    """A migration could not be started (bad handle / target tier)."""


def _lag(bucket) -> int:
    """Event-delivery lag of a bucket in flushes: 1 for a deferred device
    bucket -- ``pipeline`` or ``cross_tick``, which shift delivery by the
    same single tick (aoi._TPUBucket._defer) -- else 0.  The row-sharded
    bucket accepts both flags for symmetry but flushes synchronously (no
    ``_inflight``), and host buckets publish inline."""
    return 1 if ((getattr(bucket, "pipeline", False)
                  or getattr(bucket, "cross_tick", False))
                 and hasattr(bucket, "_inflight")) else 0


def _crc_pair(d) -> int:
    crc = zlib.crc32(np.ascontiguousarray(d[0], np.int32).tobytes())
    return zlib.crc32(np.ascontiguousarray(d[1], np.int32).tobytes(), crc)


def _target_fault_count(bucket) -> int:
    st = getattr(bucket, "stats", None)
    if st is None:
        return 0
    return (st.get("rebuilds", 0) + st.get("fallbacks", 0)
            + st.get("host_ticks", 0))


class _Migration:
    """One live migration in its double-cover phase.

    Created by :meth:`PlacementController.migrate` AFTER snapshot+replay;
    registered on the engine, which calls :meth:`on_flush_begin` /
    :meth:`on_flush_end` around every flush and forwards submits and
    maintenance to the target while the cover runs.
    """

    def __init__(self, engine, handle, target):
        self.engine = engine
        self.h = handle          # source: still owns delivery
        self.t = target          # replayed shell handle
        self.lag_s = _lag(handle.bucket)
        self.lag_t = _lag(target.bucket)
        # aligned verified comparisons before the swap.  With both sides
        # pipelined the first aligned pair is the trivially-empty warmup
        # flush, so one more is required to cover a real tick.
        self.need = 1 + min(self.lag_s, self.lag_t)
        self.verified = 0
        self.src_seq: list = []  # per-flush (enter, leave) deltas
        self.tgt_seq: list = []
        self.crc = 0             # running CRC over the verified deltas
        self.done = False
        self._src_pre = None
        self._t_faults0 = _target_fault_count(target.bucket)
        self.t0 = time.perf_counter()

    # -- engine hooks -----------------------------------------------------

    def on_submit(self, x, z, radius, active) -> None:
        """Duplicate the source's staged tick onto the target (double
        compute: same inputs, both homes)."""
        self.t.bucket.stage(self.t.slot, (x, z, radius, active))

    def on_flush_begin(self) -> None:
        # publish REPLACES a slot's pending tuple (callers consume every
        # tick), so "what did this flush publish" is an identity question:
        # a fresh tuple at flush end IS the flush's delta
        self._src_pre = self.h.bucket._events.get(self.h.slot)

    def on_flush_end(self) -> None:
        if self.done:
            return
        cur = self.h.bucket._events.get(self.h.slot)
        ds = cur if (cur is not None and cur is not self._src_pre) \
            else (_EMPTY, _EMPTY)
        # the target's published copies are DUPLICATES while the source
        # owns delivery: consume them into the cover buffer so they can
        # neither leak to the caller nor be silently replaced unseen
        dt_ = self.t.bucket._events.pop(self.t.slot, None)
        if dt_ is None:
            dt_ = (_EMPTY, _EMPTY)
        self.src_seq.append((np.asarray(ds[0]), np.asarray(ds[1])))
        self.tgt_seq.append((np.asarray(dt_[0]), np.asarray(dt_[1])))
        if _target_fault_count(self.t.bucket) != self._t_faults0:
            # the target absorbed a device fault mid-cover.  Its recovery
            # is bit-exact (the deltas still match), but a home that
            # faulted during its own audition is not a home to adopt --
            # and the bench's rollback contract (aoi.h2d:oom mid-cover
            # -> source keeps serving, zero loss) keys off exactly this.
            self.abort("target bucket faulted during cover")
            return
        k = len(self.src_seq)
        L = self.lag_t - self.lag_s
        if L >= 0:
            i, j = k - 1 - L, k - 1     # src index partnered with newest tgt
            lead = self.tgt_seq[j] if i < 0 else None
        else:
            i, j = k - 1, k - 1 + L     # newest src partnered with older tgt
            lead = self.src_seq[i] if j < 0 else None
        if lead is not None:
            # cadence warmup: the faster side has not produced the slower
            # side's first covered tick yet -- the unpartnered delta must
            # be empty or the streams can never align
            if len(lead[0]) or len(lead[1]):
                self.abort("cadence misalignment at cover start")
            return
        ds, dt_ = self.src_seq[i], self.tgt_seq[j]
        crc_s, crc_t = _crc_pair(ds), _crc_pair(dt_)
        if crc_s != crc_t or not (np.array_equal(ds[0], dt_[0])
                                  and np.array_equal(ds[1], dt_[1])):
            self.abort("event delta mismatch between source and target")
            return
        self.crc = zlib.crc32(crc_s.to_bytes(4, "little"), self.crc)
        self.verified += 1
        if self.verified >= self.need:
            with _T.span("aoi.migrate.swap"):
                self._swap()

    # -- terminal transitions ---------------------------------------------

    def _finish(self) -> None:
        self.done = True
        if getattr(self.h, "_migration", None) is self:
            del self.h._migration
        if self in self.engine._migrations:
            self.engine._migrations.remove(self)

    def abort(self, reason: str) -> None:
        """Roll back to the source bucket: drop the replayed target slot.
        The source never stopped serving, so nothing is lost."""
        if self.done:
            return
        from ..utils import gwlog

        self._finish()
        self.engine.release_space(self.t)
        self.engine.migration_stats["migration_rollbacks"] += 1
        gwlog.logger("gw.aoi").warning(
            "live migration rolled back after %d verified flushes: %s",
            self.verified, reason)

    def _swap(self) -> None:
        """Atomic ownership swap at the end of a verified flush.

        Undelivered events are reconciled by cadence lag L = lag_t - lag_s
        (ticks staged through flush k; the caller consumes events every
        tick, so the source's pending is exactly this flush's delta):

          L == 0: the source's pending becomes the target slot's pending
                  (the target's own copies were drained into the cover
                  buffer -- they were already delivered from the source).
          L == 1: nothing is owed now -- the source's pending re-delivers
                  from the target's in-flight tick, bit-exact, one tick
                  later (the space adopts the pipelined cadence).
          L == -1: the source's pending tick AND the target's newest delta
                  deliver together -- the space catches up to the
                  unpipelined cadence in one tick.

        The source slot's release bumps its epoch, so a still-in-flight
        source tick can neither publish nor XOR (no duplicates); dropping
        an exclusive source bucket frees its device state.
        """
        h, nh, eng = self.h, self.t, self.engine
        src_bucket, src_slot = h.bucket, h.slot
        L = self.lag_t - self.lag_s
        sp = src_bucket._events.pop(src_slot, None)
        owed = None
        if L == 0:
            owed = sp
        elif L < 0:
            s_e, s_l = sp if sp is not None else (_EMPTY, _EMPTY)
            t_e, t_l = self.tgt_seq[-1]
            owed = (np.concatenate([s_e, t_e]), np.concatenate([s_l, t_l]))
        if owed is not None and (len(owed[0]) or len(owed[1])):
            nh.bucket._events[nh.slot] = owed
        # the Space's handle object never changes: re-point it in place
        h.bucket, h.slot, h.backend = nh.bucket, nh.slot, nh.backend
        h.capacity = nh.capacity
        h.requested = nh.requested or h.requested
        nh.released = True  # shell handle; h owns the slot now
        self._finish()
        src_bucket.release_slot(src_slot)
        if getattr(src_bucket, "exclusive", False):
            for k, b in list(eng._buckets.items()):
                if b is src_bucket:
                    del eng._buckets[k]
        eng.migration_stats["migrations"] += 1
        eng.migration_stats["migration_ms"] += (
            time.perf_counter() - self.t0) * 1e3


@dataclass
class LoadSample:
    """One bucket's load since the controller's previous step."""

    key: tuple
    tier: str
    entities: int       # occupied slots
    flush_ms: float     # bucket flush seconds per tick, in ms
    h2d_bytes: float    # staged wire bytes per tick


def _load_samples(engine, base: dict, tick: int) -> list:
    """Per-bucket load since the caller's previous call (deterministic
    order).  ``base`` is the caller-owned {key: (perf, h2d, tick)} floor;
    PlacementController and CohortPlanner each keep their own so their
    sampling windows stay independent."""
    out = []
    for key in sorted(engine._buckets):
        b = engine._buckets[key]
        perf = sum(getattr(b, "perf", {}).values())
        h2d = getattr(b, "stats", {}).get("h2d_bytes", 0)
        base_p, base_h, base_t = base.get(key, (0.0, 0, tick - 1))
        dt = max(1, tick - base_t)
        out.append(LoadSample(
            key=key, tier=engine._tier_of(b),
            entities=b.n_slots - len(b._free),
            flush_ms=(perf - base_p) * 1e3 / dt,
            h2d_bytes=(h2d - base_h) / dt))
        base[key] = (perf, h2d, tick)
    return out


def _first_live_handle(engine, bucket):
    live = [h for h in engine._handles
            if h.bucket is bucket and not h.released
            and getattr(h, "_migration", None) is None]
    live.sort(key=lambda h: h.slot)
    return live[0] if live else None


class PlacementController:
    """Scores bucket placement from telemetry counters and executes live
    migrations (Runtime knob ``aoi_placement="static|auto"``).

    ``static`` never moves anything on its own; :meth:`migrate` stays
    available as the operator/bench entry point.  ``auto`` runs
    :meth:`step` once per tick (Runtime wires it after the AOI phase):
    when a host-tier bucket's per-tick flush time exceeds
    ``threshold_ms``, its busiest space is re-homed onto the device tier;
    a device bucket idling far below the threshold (entities > 0,
    flush_ms * 8 < threshold_ms) demotes one space back to the native
    host calculator.  One migration at a time, ``cooldown_ticks`` between
    decisions, so a noisy boundary cannot flap."""

    def __init__(self, engine, mode: str = "static",
                 threshold_ms: float = 5.0, cooldown_ticks: int = 64):
        if mode not in ("static", "auto"):
            raise ValueError(
                f"aoi_placement must be 'static' or 'auto', got {mode!r}")
        self.engine = engine
        self.mode = mode
        self.threshold_ms = threshold_ms
        self.cooldown_ticks = cooldown_ticks
        self._cooldown = 0
        self._tick = 0
        self._base: dict[tuple, tuple] = {}

    # -- the migration entry point ---------------------------------------

    def migrate(self, h, tier: str) -> _Migration:
        """Start a live migration of one space to ``tier`` (``cpu`` |
        ``cpp`` | ``tpu`` | ``mesh`` | ``rowshard``): snapshot + replay
        now, double-cover over the next flush(es), swap on verified
        parity.  Returns the in-flight :class:`_Migration`."""
        eng = self.engine
        if h.released:
            raise MigrationError("cannot migrate a released handle")
        if getattr(h, "_migration", None) is not None:
            raise MigrationError("handle is already migrating")
        with _T.span("aoi.migrate"):
            with _T.span("aoi.migrate.snapshot"):
                snap = h.bucket.export_snapshot(h.slot)
            with _T.span("aoi.migrate.replay"):
                nh = eng._create_handle(h.capacity, tier)
                nh.bucket.import_snapshot(nh.slot, snap)
            mig = _Migration(eng, h, nh)
            h._migration = mig
            eng._migrations.append(mig)
        return mig

    # -- telemetry-driven scoring ----------------------------------------

    def load_samples(self) -> list[LoadSample]:
        """Per-bucket load since the previous call (deterministic order)."""
        return _load_samples(self.engine, self._base, self._tick)

    def _first_handle(self, bucket):
        return _first_live_handle(self.engine, bucket)

    def decide(self) -> tuple | None:
        """(handle, target_tier) for the single most pressing move, or
        None.  Promotion (host -> device) outranks demotion."""
        eng = self.engine
        samples = self.load_samples()
        device_tier = "mesh" if eng.mesh is not None else "tpu"
        promote = [s for s in samples
                   if s.tier in ("cpu", "cpp") and s.entities
                   and s.flush_ms > self.threshold_ms]
        if promote:
            worst = max(promote, key=lambda s: s.flush_ms)
            h = self._first_handle(eng._buckets[worst.key])
            if h is not None:
                return h, device_tier
        demote = [s for s in samples
                  if s.tier in ("tpu", "mesh") and s.entities
                  and s.flush_ms * 8 < self.threshold_ms]
        if demote:
            idlest = min(demote, key=lambda s: s.flush_ms)
            h = self._first_handle(eng._buckets[idlest.key])
            if h is not None:
                return h, "cpp"
        return None

    def settle(self, ticks: int | None = None) -> None:
        """Hold auto placement decisions for ``ticks`` (default: one
        cooldown window).  Host-failover re-homing calls this after
        restoring a dead game's spaces (docs/robustness.md "Cluster
        supervision & host failover"): the first post-restore flushes are
        warm-up noise -- fresh bases, cold device state -- and scoring
        them would migrate spaces mid-recovery, stretching
        ticks_to_recover for nothing."""
        self._cooldown = max(
            self._cooldown,
            self.cooldown_ticks if ticks is None else int(ticks))

    def step(self) -> None:
        """One controller tick (Runtime calls this after the AOI phase).
        The double-cover itself is driven by engine.flush; this only makes
        new placement decisions, and only in ``auto`` mode."""
        self._tick += 1
        if self.mode != "auto":
            return
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        if self.engine._migrations:
            return  # one live migration at a time
        plan = self.decide()
        if plan is not None:
            h, tier = plan
            try:
                self.migrate(h, tier)
            except MigrationError:
                pass  # raced with a release; score again next window
            self._cooldown = self.cooldown_ticks


class CohortPlanner:
    """Telemetry-driven cohort membership (Runtime knob
    ``aoi_cohort_planner="static|auto"``, docs/perf.md "Space-stacked
    cohorts").

    Scores the cohort tier the way :class:`PlacementController` scores
    bucket tiers -- per-bucket flush-ms deltas from the same counters the
    telemetry registry exports -- and re-buckets membership live through
    :meth:`AOIEngine.cohort_join` / :meth:`AOIEngine.cohort_leave` (the
    snapshot seam; between-flush, bit-exact).  Two rules, both bounded:

      * a cohort whose shared launch exceeds ``hot_ms`` sheds one member
        per window -- one hot space must not gate the whole cohort's
        fused launch (per-member attribution is not collected, so the
        lowest slot goes: shedding ANY member shrinks the launch);
      * a light solo space -- planner leave and ``aoi.cohort`` fault
        demotion alike -- folds back into its ladder cohort, so the
        planner doubles as the demotion re-arm loop.

    Churn discipline: at most ``churn_budget`` moves per decision window
    and ``cooldown_ticks`` quiet ticks after any move, and target shapes
    only ever come from the engine's pow2 ladder -- membership churn
    re-buckets spaces between EXISTING jit keys, so steady-state
    recompiles stay at 0 (the bench pin)."""

    def __init__(self, engine, mode: str = "static", hot_ms: float = 8.0,
                 churn_budget: int = 2, cooldown_ticks: int = 32):
        if mode not in ("static", "auto"):
            raise ValueError(
                f"aoi_cohort_planner must be 'static' or 'auto', "
                f"got {mode!r}")
        self.engine = engine
        self.mode = mode
        self.hot_ms = hot_ms
        self.churn_budget = churn_budget
        self.cooldown_ticks = cooldown_ticks
        self._cooldown = 0
        self._tick = 0
        self._base: dict[tuple, tuple] = {}

    def load_samples(self) -> list[LoadSample]:
        """Per-bucket load since the previous call (own window, so the
        placement controller's sampling is undisturbed)."""
        return _load_samples(self.engine, self._base, self._tick)

    def decide(self) -> list[tuple]:
        """[(handle, "leave"|"join"), ...] for this window, budget-bounded
        and deterministic (bucket-key order, hot leaves first)."""
        eng = self.engine
        samples = self.load_samples()
        plan: list[tuple] = []
        for s in samples:
            if len(plan) >= self.churn_budget:
                return plan
            b = eng._buckets.get(s.key)
            if (b is not None and getattr(b, "cohort", False)
                    and s.entities > 1 and s.flush_ms > self.hot_ms):
                h = _first_live_handle(eng, b)
                if h is not None:
                    plan.append((h, "leave"))
        for s in samples:
            if len(plan) >= self.churn_budget:
                return plan
            b = eng._buckets.get(s.key)
            if (b is not None and getattr(b, "cohort_solo", False)
                    and s.entities and s.flush_ms * 4 < self.hot_ms):
                h = _first_live_handle(eng, b)
                if h is not None:
                    plan.append((h, "join"))
        return plan

    def step(self) -> None:
        """One planner tick (Runtime wires it after placement.step)."""
        self._tick += 1
        if self.mode != "auto":
            return
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        moved = 0
        for h, action in self.decide():
            if h.released:
                continue  # raced with a release inside the window
            if action == "leave":
                self.engine.cohort_leave(h)
            else:
                self.engine.cohort_join(h)
            moved += 1
        if moved:
            self._cooldown = self.cooldown_ticks
