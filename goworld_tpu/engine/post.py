"""The post queue: the only safe way for worker threads / callbacks to run
code on the logic thread (reference: /root/reference/engine/post/post.go:21-44,
drained at the end of every main-loop iteration).

Thread-safe enqueue; single-consumer ``tick`` drains.  Callbacks posted while
draining run in the *next* drain (same as the reference's swap semantics),
so a callback that re-posts itself cannot starve the loop.
"""

from __future__ import annotations

import threading
from typing import Callable


class PostQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue: list[Callable[[], None]] = []

    def post(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._queue.append(fn)

    def tick(self, on_error: Callable[[BaseException], None] | None = None) -> int:
        with self._lock:
            batch, self._queue = self._queue, []
        for fn in batch:
            try:
                fn()
            except Exception as e:  # crash isolation, reference gwutils idiom
                if on_error:
                    on_error(e)
                else:
                    raise
        return len(batch)

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)


_default = PostQueue()


def post(fn: Callable[[], None]) -> None:
    """Post to the process-wide default queue."""
    _default.post(fn)


def tick(on_error=None) -> int:
    return _default.tick(on_error)
