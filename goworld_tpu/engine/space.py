"""Space: a shard of the world holding co-located entities.

A Space is itself an entity (reference: Space.go:14 ``__space__``); it owns
the per-space AOI arrays and its handle into the process AOIEngine.  All
entities in a space are co-located on one game process (and their AOI rows on
one chip) -- this is the framework's unit of sharding.

Batched AOI protocol per tick (north-star hot loop; reference equivalent:
Space.go:188-261 enter/leave/move -> go-aoi callbacks):

    * ``enter_entity``/``leave_entity``/``move_entity`` update the packed
      per-slot arrays (x, z, radius, active) incrementally -- O(1) each;
    * the runtime's tick calls ``submit_aoi`` then ``AOIEngine.flush`` then
      ``dispatch_aoi_events``, which replays enter/leave pairs (sorted,
      deterministic) through Entity._interest/_uninterest.

The nil space (reference: Space.go:127-140) is a kindless space with AOI
disabled where entities live when not in a real space.
"""

from __future__ import annotations

import numpy as np

from .ecs import ColumnStore
from .entity import Entity
from .vector import Vector3

SPACE_TYPE_NAME = "__space__"
_MIN_CAPACITY = 128


class Space(Entity):
    # spaces are never AOI members themselves
    use_aoi = False

    def __init__(self):
        super().__init__()
        self.kind = 0
        self.entities: set[Entity] = set()
        self._aoi_handle = None
        self._aoi_default_dist = 0.0
        # columnar ECS store (engine/ecs.py): the hot per-slot attributes
        # (x/z/r/act/nonplain + the y/yaw/sync/watched host companions)
        # as capacity-sized arrays grown by doubling.  Entities hold VIEWS
        # into these columns while slotted (Entity.position); submit_aoi
        # hands the calculator the columns themselves, so the flush()
        # delta diff reads them directly -- no per-entity walk anywhere
        self._cap = 0
        self._cols = ColumnStore()
        self._slot_entity: list[Entity | None] = []
        # numpy object-array mirror of _slot_entity: event replay fancy-
        # indexes whole pair columns at C speed instead of per-pair list
        # lookups (dispatch_aoi_events)
        self._slot_np = np.empty(0, object)
        self._free_slots: list[int] = []
        # two-stage cooling for freed slots: a pipelined calculator's events
        # for a slot freed during tick T are dispatched at T and only
        # DELIVERED at T+1's AOI phase, so the slot must stay unallocatable
        # through the end of T+1 -- not just this tick's phase (timers and
        # user code between ticks allocate too).  recycle_aoi_slots advances
        # cooling -> cooling2 -> free at the end of each AOI phase.
        self._free_cooling: list[int] = []
        self._free_cooling2: list[int] = []
        self._slot_watermark = 0
        self._aoi_dirty = False
        # event-stream subscription last applied to the calculator: a space
        # with no nonplain entity opts out (set_subscribed) so device
        # backends skip its extraction/fetch/decode entirely
        self._aoi_subscribed = True

    @property
    def is_space(self) -> bool:
        return True

    @property
    def is_nil(self) -> bool:
        return self.kind == 0

    # legacy accessors for the packed arrays -- the columns ARE the
    # arrays now (ColumnStore); kept so calculators, tests and tools that
    # index `space._x[slot]` keep working against the live column
    @property
    def _x(self) -> np.ndarray:
        return self._cols.x

    @property
    def _z(self) -> np.ndarray:
        return self._cols.z

    @property
    def _r(self) -> np.ndarray:
        return self._cols.r

    @property
    def _act(self) -> np.ndarray:
        return self._cols.act

    @property
    def _nonplain(self) -> np.ndarray:
        return self._cols.nonplain

    def on_space_init(self):  # user hook (reference ISpace)
        pass

    def on_entity_enter_space(self, e: Entity):
        pass

    def on_entity_leave_space(self, e: Entity):
        pass

    # -- AOI management ----------------------------------------------------
    def enable_aoi(self, default_dist: float, backend: str | None = None,
                   capacity: int | None = None):
        """Turn on interest management for this space (reference:
        EnableAOI, Space.go:91-107).  Must be called before entities enter.

        ``capacity`` pre-sizes the space: population grows capacity on
        demand anyway, but an expected-oversized space (>= the row-shard
        threshold) should pre-size so it lands on the row-sharded
        calculator directly instead of repacking through every doubling."""
        if self._aoi_handle is not None:
            raise RuntimeError("AOI already enabled")
        if self.entities:
            raise RuntimeError("enable AOI before entities enter the space")
        self._aoi_default_dist = float(default_dist)
        self._ensure_capacity(max(_MIN_CAPACITY, int(capacity or 0)))
        self._aoi_handle = self._runtime().aoi.create_space(self._cap, backend)

    @property
    def aoi_enabled(self) -> bool:
        return self._aoi_handle is not None

    def enable_interest(self, *policies, mode: str | None = None):
        """Attach a composable interest-policy stack to this space
        (goworld_tpu/interest/): team/faction visibility, tiered update
        rates, line-of-sight occlusion -- fused into one device pass and
        composed with the base radius predicate.  Requires ``enable_aoi``
        first; like it, must run before entities enter (the stack's
        previous-step state starts empty).  Returns the PolicyStack."""
        if self._aoi_handle is None:
            raise RuntimeError("enable_aoi before enable_interest")
        if self.entities:
            raise RuntimeError(
                "enable interest policies before entities enter the space")
        return self._runtime().aoi.attach_interest(
            self._aoi_handle, policies, mode=mode)

    @property
    def interest_stack(self):
        """The attached PolicyStack, or None (radius-only space)."""
        h = self._aoi_handle
        return None if h is None else getattr(h, "_policy_stack", None)

    def set_aoi_team(self, e: Entity, team: int, vis: int | None = None):
        """Set an entity's faction columns (team_mask policy semantics:
        observer A sees B iff ``vis[A] & team[B] != 0``).  ``team`` is
        B-side (what bitmask the entity presents), ``vis`` is A-side
        (which team bits the entity can see); ``vis=None`` keeps the
        current visibility mask.  Entities enter with team=1,
        vis=0xFFFFFFFF -- mutually visible until told otherwise."""
        if e.space is not self or e.aoi_slot < 0:
            raise ValueError(f"{e} holds no AOI slot in this space")
        cols = self._cols
        cols.team[e.aoi_slot] = np.uint32(team)
        if vis is not None:
            cols.vis[e.aoi_slot] = np.uint32(vis)
        self._aoi_dirty = True

    def _ensure_capacity(self, n: int):
        if n <= self._cap:
            return
        new_cap = max(_MIN_CAPACITY, self._cap or _MIN_CAPACITY)
        while new_cap < n:
            new_cap *= 2
        self._cols.ensure_capacity(new_cap)
        self._slot_entity.extend([None] * (new_cap - len(self._slot_entity)))
        slot_np = np.empty(new_cap, object)
        slot_np[: len(self._slot_np)] = self._slot_np
        self._slot_np = slot_np
        old_cap = self._cap
        self._cap = new_cap
        if self._aoi_handle is not None and old_cap:
            self._aoi_handle = self._runtime().aoi.grow_space(
                self._aoi_handle, new_cap
            )
            # the fresh bucket slot defaults to subscribed; reset the cached
            # flag so the next submit re-applies an unsubscription (an
            # all-plain space must not silently resume event extraction)
            self._aoi_subscribed = True

    # -- membership --------------------------------------------------------
    def enter_entity(self, e: Entity, pos: Vector3, is_restore: bool = False):
        """Reference: Space.enter, Space.go:188-226.  ``is_restore``
        re-establishes membership after freeze-restore WITHOUT firing the
        user enter hooks (reference: restore re-enters quietly,
        EntityManager.go:591-652 -- a restore reconstructs state, it is not
        a new enter; hooks like the demo's spawn-monsters-per-player must
        not re-fire)."""
        if e.space is not None:
            raise ValueError(f"{e} already in a space")
        e.space = self
        e.position = pos
        self.entities.add(e)
        if self._aoi_handle is not None and e.use_aoi:
            if self._free_slots:
                slot = self._free_slots.pop()
            else:
                slot = self._next_slot()
            e.aoi_slot = slot
            self._slot_entity[slot] = e
            self._slot_np[slot] = e
            cols = self._cols
            cols.nonplain[slot] = not e._plain_aoi
            cols.x[slot] = pos.x
            cols.y[slot] = pos.y
            cols.z[slot] = pos.z
            cols.yaw[slot] = e._yaw
            cols.r[slot] = (
                e.aoi_distance if e.aoi_distance > 0 else self._aoi_default_dist
            )
            cols.act[slot] = True
            # faction defaults: on one team, sees everyone -- a space with
            # a team_mask policy behaves exactly radius-like until
            # set_aoi_team says otherwise
            cols.team[slot] = np.uint32(1)
            cols.vis[slot] = np.uint32(0xFFFFFFFF)
            cols.sync[slot] = 0
            cols.watched[slot] = (e._watcher_clients > 0
                                  or e.client is not None)
            self._aoi_dirty = True
        if not is_restore:
            self.on_entity_enter_space(e)
            e.on_enter_space()

    def _next_slot(self) -> int:
        if self._slot_watermark >= self._cap:
            self._ensure_capacity(self._cap + 1)
        slot = self._slot_watermark
        self._slot_watermark += 1
        return slot

    def leave_entity(self, e: Entity):
        """Reference: Space.leave, Space.go:228-251."""
        if e.space is not self:
            return
        if e.aoi_slot >= 0:
            slot = e.aoi_slot
            cols = self._cols
            # detach the entity's position/yaw views: snapshot the column
            # values back into the f64 Vector3 the views fall through to
            # (batched moves and ingest write columns only, so the
            # snapshot may be the ONLY up-to-date copy)
            p = e._pos
            p.x = float(cols.x[slot])
            p.y = float(cols.y[slot])
            p.z = float(cols.z[slot])
            e._yaw = float(cols.yaw[slot])
            cols.clear_slot(slot)
            self._slot_entity[slot] = None
            self._slot_np[slot] = None
            self._free_cooling.append(slot)
            e.aoi_slot = -1
            self._aoi_dirty = True
            # erase the slot from the calculator's previous-tick state: the
            # interests are severed synchronously below, so the batched diff
            # must not re-emit them (and a reused slot must start clean)
            self._runtime().aoi.clear_entity(self._aoi_handle, slot)
            # departure events must fire this tick; sever interests now so
            # callbacks and client destroys are immediate and deterministic
            for other in list(e.interested_in):
                e._uninterest(other)
            for other in list(e.interested_by):
                other._uninterest(e)
        self.entities.discard(e)
        e.space = None
        self.on_entity_leave_space(e)
        e.on_leave_space(self)

    def move_entities(self, slots, xs, zs, ys=None, yaws=None):
        """Batched position update: one call moves many entities (reference
        analog: the gate->game client-sync path decodes a flat array of
        positions and applies them in one pass, GameService.go:398-410).
        All position/yaw writes are vectorized column writes (entities
        VIEW the columns -- engine/ecs.py -- so nothing per-entity needs
        updating); sync bookkeeping runs just for entities some client can
        actually see.  This is the device-cadence movement path: at 64k
        entities it costs ~20 ms where per-entity set_position costs
        ~100 ms.  (The fully-batched wire path, goworld_tpu/ingest/,
        replaces even the bookkeeping loop with a sync-column write.)

        With ``ys``/``yaws`` (the client-sync ingest,
        sync_entities_from_client) height and yaw update too."""
        slots = np.asarray(slots, np.int64)
        cols = self._cols
        cols.x[slots] = xs
        cols.z[slots] = zs
        if ys is not None:
            cols.y[slots] = ys
            cols.yaw[slots] = yaws
        self._aoi_dirty = True
        se = self._slot_np
        # sync bookkeeping (client-driven entities get no owner echo --
        # same rule as set_position: correcting the owner fights
        # client-side prediction; server-driven ones do).  Inlined, not a
        # helper: a per-entity call costs ~5 ms at 64k on the
        # device-cadence path.
        for s in slots.tolist():
            e = se[s]
            if e is None:
                continue
            if e._watcher_clients > 0 or e.client is not None:
                e._sync_flags |= 2 if e.client_syncing else 3
                ds = e._dirty_set
                if ds is not None:
                    ds.add(e)

    def sync_entities_from_client(self, slots, xs, ys, zs, yaws):
        """Batched client-driven position/yaw sync: the gate->game sync
        packet decodes into flat arrays and applies in one pass (reference:
        GameService.go:398-410 decodes the flat sync array;
        Entity.go:1221-1267 batches the outbound half).  Semantically one
        ``sync_position_yaw_from_client`` per entry; shares move_entities'
        apply loop -- the sync-flag policy there already reduces to
        SYNC_NEIGHBORS-only for client-syncing entities (no owner echo:
        correcting the owner fights client-side prediction)."""
        self.move_entities(slots, xs, zs, ys=ys, yaws=yaws)

    def move_entity(self, e: Entity, pos: Vector3):
        """Reference: Space.move, Space.go:253-261.  (Entity.set_position
        inlines this; other callers use it directly.)  The position
        assignment writes the columns and marks AOI dirty when slotted
        (Entity.position setter)."""
        e.position = pos

    # -- per-tick AOI ------------------------------------------------------
    def recycle_aoi_slots(self):
        """Advance the two-stage cooling pipeline (see ``_free_cooling``).
        Called at the END of each AOI phase, after event delivery: a slot
        freed during tick T becomes allocatable only after T+1's delivery
        of the events dispatched while it was live."""
        if self._free_cooling2:
            self._free_slots.extend(self._free_cooling2)
            self._free_cooling2.clear()
        if self._free_cooling:
            self._free_cooling2.extend(self._free_cooling)
            self._free_cooling.clear()

    def submit_aoi(self) -> bool:
        """Stage this tick's arrays if anything changed; returns staged?"""
        if self._aoi_handle is None or not self._aoi_dirty:
            return False
        aoi = self._runtime().aoi
        stack = getattr(self._aoi_handle, "_policy_stack", None)
        # subscription tracks "does anyone consume events?": pairs whose
        # observer is plain are dropped at delivery anyway, so an all-plain
        # space needs no event stream at all -- the calculator skips its
        # extraction/fetch/decode and interest state derives on demand.
        # With an interest stack attached the BUCKET's stream is never
        # consumed at all (the stack owns take_events), so the bucket
        # unsubscribes outright while still carrying the base state.
        cols = self._cols
        sub = (stack is None
               and bool(cols.nonplain[: self._slot_watermark].any()))
        if sub != self._aoi_subscribed:
            self._aoi_subscribed = sub
            aoi.set_subscribed(self._aoi_handle, sub)
        # the columns ARE the staged arrays: flush()'s delta diff
        # (engine/aoi._stage_inputs) reads them directly against the host
        # shadows -- wire/logic writes land here vectorized and nothing
        # walks entities between a move and the H2D packet
        aoi.submit(self._aoi_handle, cols.x, cols.z, cols.r, cols.act)
        if stack is not None:
            stack.submit(cols.x, cols.z, cols.r, cols.act,
                         cols.team, cols.vis)
        self._aoi_dirty = False
        return True

    def drain_column_sync(self):
        """Fold pending column sync flags (set vectorized by the batched
        ingest path, goworld_tpu/ingest/) into the per-entity sync
        machinery.  One vectorized scan finds flagged slots; only WATCHED
        movers (some client can see them -- the ``watched`` column) pay a
        per-entity visit, which routes through ``_sync_flags`` +
        the dirty set so records emit exactly once per entity per tick
        even when batched and per-entity writes mix."""
        cols = self._cols
        sf = cols.sync[: self._slot_watermark]
        idx = np.nonzero(sf)[0]
        if not idx.size:
            return
        flags = sf[idx].copy()
        sf[idx] = 0
        w = cols.watched[idx]
        se = self._slot_np
        for s, f in zip(idx[w].tolist(), flags[w].tolist()):
            e = se[s]
            if e is None or e.destroyed:
                continue
            e._sync_flags |= f
            ds = e._dirty_set
            if ds is not None:
                ds.add(e)

    def dispatch_aoi_events(self):
        """Replay batched enter/leave pairs through entity interest hooks.

        Fast path: a pair whose OBSERVER has no client and default AOI hooks
        (``_plain_aoi``) is pure interest-set bookkeeping -- two C-level set
        ops, no method dispatch.  Observers with a client or overridden
        hooks take the full ``_interest``/``_uninterest`` path (client
        create/destroy ops, watcher counts, user callbacks).  Slot->entity
        resolution fancy-indexes the object-array mirror: one C pass per
        event batch instead of two list lookups per pair."""
        if self._aoi_handle is None:
            return
        enter, leave = self._runtime().aoi.take_events(self._aoi_handle)
        se = self._slot_np
        nonplain = self._nonplain
        # leaves first: a slot reused within one tick (leave+enter) must
        # destroy before re-creating on clients.  Pairs with a PLAIN
        # observer are dropped wholesale (one vectorized mask): their
        # interest state is the calculator's packed words, derived on
        # demand -- no per-pair host work at all.
        if len(leave):
            need = leave[nonplain[leave[:, 0]]]
            for a, b in zip(se[need[:, 0]], se[need[:, 1]]):
                if a is not None and b is not None:
                    a._uninterest(b)
        if len(enter):
            need = enter[nonplain[enter[:, 0]]]
            for a, b in zip(se[need[:, 0]], se[need[:, 1]]):
                if a is not None and b is not None:
                    a._interest(b)

    # -- lazy interest derivation ------------------------------------------
    def derive_interests(self, slot: int) -> list[Entity]:
        """Entities the slot's entity is interested in, derived from the
        calculator's packed interest words (post-last-flush state).  This is
        how PLAIN entities -- no client, default hooks -- answer
        ``neighbors()`` without any per-event host bookkeeping: the
        authoritative interest state never leaves the packed representation
        until someone actually asks."""
        h = self._aoi_handle
        if h is None or slot < 0:
            return []
        stack = getattr(h, "_policy_stack", None)
        if stack is not None:
            # policy space: the stack's post-step words ARE the interest
            # state (the bucket's base words ignore team/tier/los)
            row = stack.words[slot]
        else:
            derive = getattr(h.bucket, "derive_row", None)
            if derive is not None:
                # row-sharded oversized space: fetch ONE observer's words
                # [W] (16 KB) instead of materializing the full [C, W]
                row = derive(h.slot, slot)
            else:
                words = h.bucket.peek_words(h.slot)
                if words is None:
                    words = h.bucket.get_prev(h.slot)
                row = words[slot]
        w_per = row.shape[0]
        sn = self._slot_np
        out = []
        for w in np.nonzero(row)[0]:
            bits = int(row[w])
            while bits:
                k = (bits & -bits).bit_length() - 1
                bits &= bits - 1
                e = sn[k * w_per + w]  # planar layout: j = k*W + w
                if e is not None:
                    out.append(e)
        return out

    def derive_observers(self, slot: int) -> list[Entity]:
        """Entities interested IN the slot's entity (the packed column)."""
        h = self._aoi_handle
        if h is None or slot < 0:
            return []
        stack = getattr(h, "_policy_stack", None)
        derive = getattr(h.bucket, "derive_col", None)
        if stack is None and derive is not None:
            rows = derive(h.slot, slot)
        else:
            if stack is not None:
                words = stack.words
            else:
                words = h.bucket.peek_words(h.slot)
                if words is None:
                    words = h.bucket.get_prev(h.slot)
            from ..ops import aoi_predicate as AP

            w, b = AP.word_bit_for_column(slot, self._cap)
            rows = np.nonzero(words[:, w] & (np.uint32(1) << np.uint32(b)))[0]
        sn = self._slot_np
        return [sn[i] for i in rows if sn[i] is not None]

    # -- destroy -----------------------------------------------------------
    def _destroy_impl(self, is_migrate: bool):
        for e in list(self.entities):
            e.destroy()
        if self._aoi_handle is not None:
            self._runtime().aoi.release_space(self._aoi_handle)
            self._aoi_handle = None
        super()._destroy_impl(is_migrate)
