"""Entity: the unit of game logic.

Re-design of the reference's Entity (/root/reference/engine/entity/Entity.go:44-70):
identity, attribute tree with client replication classes, RPC, timers, space
membership, AOI interest sets, client binding, migration data.  Differences
from the reference are deliberate and TPU/batch-first:

  * AOI events arrive *batched per tick* from the space's calculator (see
    engine/aoi.py) instead of synchronously during moves;
  * client-bound traffic (creates/destroys/attr deltas/position sync) is
    accumulated per tick and flushed by the runtime's sync phase, mirroring
    the reference's own batched position sync (Entity.go:1221-1267) but
    applied uniformly;
  * RPC exposure is declared with decorators (engine/rpc.py), not name
    suffixes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Iterable

from .attrs import MapAttr
from .ecs import PositionView
from .vector import Vector3

if TYPE_CHECKING:
    from .manager import EntityManager, EntityTypeDesc
    from .space import Space

# sync-info flags (reference: sifSyncOwnClient/sifSyncNeighborClients,
# Entity.go:1199-1204)
SYNC_OWN = 1
SYNC_NEIGHBORS = 2


class GameClient:
    """Server-side handle to a client connection (reference: GameClient.go).

    Wire ops accumulate in ``outbox`` as (op, *payload) tuples; the runtime's
    sync phase drains them into per-gate packets.  In single-process tests the
    outbox is inspected directly.
    """

    __slots__ = ("client_id", "gate_id", "outbox", "on_dirty")

    def __init__(self, client_id: str, gate_id: int = 0, on_dirty=None):
        self.client_id = client_id
        self.gate_id = gate_id
        self.outbox: list[tuple] = []
        # called on the first op after each drain, so the host component
        # visits only clients with traffic (no per-tick all-entities scan)
        self.on_dirty = on_dirty

    def _push(self, op: tuple):
        if not self.outbox and self.on_dirty is not None:
            self.on_dirty(self)
        self.outbox.append(op)

    # -- ops toward the client (batched) ----------------------------------
    def create_entity(self, e: "Entity", is_player: bool):
        self._push(
            (
                "create_entity",
                e.type_name,
                e.id,
                is_player,
                e.client_visible_attrs(to_owner=is_player),
                e.position.to_tuple(),
                e.yaw,
            )
        )

    def destroy_entity(self, e: "Entity"):
        self._push(("destroy_entity", e.type_name, e.id))

    def attr_delta(self, eid: str, path: tuple, op: str, value: Any):
        self._push(("attr_delta", eid, path, op, value))

    def call_client(self, eid: str, method: str, args: tuple):
        self._push(("call", eid, method, args))


class Entity:
    """Base class for all game entities.  Subclass and register via
    ``EntityManager.register``."""

    # -- subclass-overridable declarations --------------------------------
    # attr replication classes, by top-level attr key
    client_attrs: frozenset[str] = frozenset()
    all_client_attrs: frozenset[str] = frozenset()
    persistent_attrs: frozenset[str] = frozenset()
    # AOI defaults for this type (reference: SetUseAOI, EntityManager.go:51-59)
    use_aoi: bool = False
    aoi_distance: float = 0.0
    # persistence (reference: EntityTypeDesc.IsPersistent)
    persistent: bool = False

    def __init__(self):
        # populated by EntityManager.create; never construct directly
        self.id: str = ""
        self.type_name: str = ""
        self.manager: "EntityManager | None" = None
        self.desc: "EntityTypeDesc | None" = None
        self.attrs = MapAttr()
        self.attrs._owner = self
        # ECS hot/cold split (engine/ecs.py): position and yaw are HOT --
        # while the entity holds an AOI slot they live in the space's
        # columns and these fields are views/fallbacks.  _pos is the
        # detached f64 snapshot (authoritative while slotless); the
        # PositionView reads/writes through to the columns when slotted.
        self._pos = Vector3()
        self._pos_view = PositionView(self)
        self._yaw: float = 0.0
        self.space: "Space | None" = None
        self.aoi_slot: int = -1  # slot in the space's arrays while in a space
        self.interested_in: set[Entity] = set()
        self.interested_by: set[Entity] = set()
        # how many of interested_by have a client -- maintained by
        # _interest/_uninterest/set_client so the sync phase can skip the
        # neighbor fanout for entities nobody's client is watching (the
        # common case: server-side mobs far from any player)
        self._watcher_clients = 0
        self.client: GameClient | None = None
        self.client_syncing = False  # accept client-originated position sync
        self._timer_ids: dict[int, tuple] = {}  # tid -> (method, interval, repeat, args)
        self._sync_flags = 0
        self._attr_deltas: list[tuple] = []  # (path, op, value) this tick
        self.destroyed = False
        # hot-path caches, set by EntityManager.create: the runtime's stable
        # dirty-set object, and whether AOI event replay for this entity is
        # pure set bookkeeping (no client, default hooks -- the batched fast
        # path in Space.dispatch_aoi_events)
        self._dirty_set: set | None = None
        self._plain_aoi = True

    # ------------------------------------------------------------------ api
    def _mark_dirty(self):
        """Register with the runtime's per-tick dirty set so the sync phase
        touches only entities that actually changed (the reference's
        CollectEntitySyncInfos scans every entity each tick, Entity.go:1221
        -- compiled Go affords that; a host-language tick loop does not)."""
        s = self._dirty_set
        if s is not None:
            s.add(self)

    def _recompute_plain(self):
        if self.desc is not None:
            self._plain_aoi = self.client is None and self.desc.plain_aoi_hooks
        else:
            cls = type(self)
            self._plain_aoi = self.client is None and (
                cls.on_enter_aoi is Entity.on_enter_aoi
                and cls.on_leave_aoi is Entity.on_leave_aoi
            )
        if self.aoi_slot >= 0 and self.space is not None:
            self.space._nonplain[self.aoi_slot] = not self._plain_aoi

    def _touch_watched(self):
        """Mirror "some client can see this entity" into the space's
        ``watched`` column (engine/ecs.py) -- the vectorized ingest path's
        sync drain filters flagged movers by it, so it must track every
        _watcher_clients / client transition while slotted."""
        slot = self.aoi_slot
        if slot >= 0 and self.space is not None:
            self.space._cols.watched[slot] = (
                self._watcher_clients > 0 or self.client is not None)

    @property
    def is_space(self) -> bool:
        return False

    def __repr__(self):
        return f"<{self.type_name}:{self.id}>"

    # -- lifecycle hooks (override in subclasses) -------------------------
    def on_init(self):  # attrs attached, not yet in any space
        pass

    def on_created(self):
        pass

    def on_game_ready(self):  # deployment barrier passed
        pass

    def on_enter_space(self):
        pass

    def on_leave_space(self, space: "Space"):
        pass

    def on_destroy(self):
        pass

    def on_enter_aoi(self, other: "Entity"):
        pass

    def on_leave_aoi(self, other: "Entity"):
        pass

    def on_client_connected(self):
        pass

    def on_client_disconnected(self):
        pass

    def on_migrate_out(self):
        pass

    def on_migrate_in(self):
        pass

    def on_freeze(self):
        pass

    def on_restored(self):
        pass

    # -- attrs ------------------------------------------------------------
    def _on_attr_delta(self, path: tuple, op: str, value: Any):
        self._attr_deltas.append((path, op, value))
        self._mark_dirty()

    def client_visible_attrs(self, to_owner: bool) -> dict:
        """Snapshot of attrs visible to a client (own client sees ``client``
        + ``all_clients`` classes; neighbors see ``all_clients`` only)."""
        keys = set(self.all_client_attrs)
        if to_owner:
            keys |= set(self.client_attrs)
        return {k: v for k, v in self.attrs.to_dict().items() if k in keys}

    def persistent_data(self) -> dict:
        return {
            k: v
            for k, v in self.attrs.to_dict().items()
            if k in self.persistent_attrs
        }

    def save(self):
        """Queue an async save of the persistent attr subset (reference:
        Entity.Save; periodic timer per save_interval, Entity.go:215-222)."""
        if not self.persistent or self.destroyed:
            return
        game = getattr(self._runtime(), "game", None)
        storage = getattr(game, "storage", None) if game is not None else None
        if storage is not None:
            storage.save(self.type_name, self.id, self.persistent_data())

    def _flush_attr_deltas(self):
        """Route this tick's attr deltas to own client / neighbor clients."""
        if not self._attr_deltas:
            return
        deltas = self._attr_deltas
        self._attr_deltas = []
        for path, op, value in deltas:
            top = path[0]
            to_owner = top in self.client_attrs or top in self.all_client_attrs
            to_neighbors = top in self.all_client_attrs
            if to_owner and self.client is not None:
                self.client.attr_delta(self.id, path, op, value)
            if to_neighbors:
                for other in self.interested_by:
                    if other.client is not None:
                        other.client.attr_delta(self.id, path, op, value)

    # -- position / AOI ----------------------------------------------------
    @property
    def position(self) -> PositionView:
        """The entity's position as a live view: component access reads
        the space's columns while the entity holds an AOI slot (f32, the
        AOI boundary precision), the detached f64 snapshot otherwise.
        It IS a Vector3 (subclass), so equality/arithmetic keep working."""
        return self._pos_view

    @position.setter
    def position(self, pos: Vector3):
        # plain assignment: update value only (no sync flags -- that is
        # set_position's job).  Read components FIRST: ``pos`` may be this
        # entity's own view.
        x, y, z = pos.x, pos.y, pos.z
        p = self._pos
        p.x = x
        p.y = y
        p.z = z
        slot = self.aoi_slot
        if slot >= 0:
            sp = self.space
            if sp is not None:
                cols = sp._cols
                cols.x[slot] = x
                cols.y[slot] = y
                cols.z[slot] = z
                sp._aoi_dirty = True

    @property
    def yaw(self) -> float:
        slot = self.aoi_slot
        if slot >= 0:
            sp = self.space
            if sp is not None:
                return float(sp._cols.yaw[slot])
        return self._yaw

    @yaw.setter
    def yaw(self, v: float):
        v = float(v)
        self._yaw = v
        slot = self.aoi_slot
        if slot >= 0:
            sp = self.space
            if sp is not None:
                sp._cols.yaw[slot] = v

    def set_position(self, pos: Vector3):
        # the single hottest host call in the engine (once per entity move
        # per tick); space.move_entity is inlined and the dirty-set add uses
        # the cached stable set
        self.position = pos
        if self.client_syncing:
            self._sync_flags |= SYNC_NEIGHBORS
        else:
            # server-driven move must also correct the owner client
            self._sync_flags |= SYNC_OWN | SYNC_NEIGHBORS
        s = self._dirty_set
        if s is not None:
            s.add(self)

    def set_yaw(self, yaw: float):
        self.yaw = float(yaw)
        self._sync_flags |= SYNC_NEIGHBORS
        if not self.client_syncing:
            self._sync_flags |= SYNC_OWN
        self._mark_dirty()

    def set_client_syncing(self, flag: bool):
        """Allow the owner client to drive this entity's position
        (reference: SetClientSyncing, Entity.go:430-440)."""
        self.client_syncing = bool(flag)

    def sync_position_yaw_from_client(self, pos: Vector3, yaw: float):
        if not self.client_syncing or self.space is None:
            return
        self.space.move_entity(self, pos)
        self.yaw = float(yaw)
        self._sync_flags |= SYNC_NEIGHBORS
        self._mark_dirty()

    # interest bookkeeping -- driven by the space's batched AOI events
    # (reference: interest/uninterest, Entity.go:236-246)
    def _interest(self, other: "Entity"):
        # flush other's pending deltas to its *pre-existing* audience before
        # we join it: the snapshot below already contains them, and a mirror
        # that applied both would double-apply non-idempotent ops (APPEND/POP)
        if self.client is not None:
            other._flush_attr_deltas()
        if other not in self.interested_in and self.client is not None:
            other._watcher_clients += 1
            other._touch_watched()
        self.interested_in.add(other)
        other.interested_by.add(self)
        if self.client is not None:
            self.client.create_entity(other, is_player=False)
        self.on_enter_aoi(other)

    def _uninterest(self, other: "Entity"):
        if other in self.interested_in and self.client is not None:
            other._watcher_clients -= 1
            other._touch_watched()
        self.interested_in.discard(other)
        other.interested_by.discard(self)
        if self.client is not None:
            self.client.destroy_entity(other)
        self.on_leave_aoi(other)

    def neighbors(self) -> Iterable["Entity"]:
        """Entities this one is currently interested in (as of the last AOI
        flush).  PLAIN entities -- no client, default hooks -- derive the
        answer from the calculator's packed interest words on demand; their
        ``interested_in``/``interested_by`` sets are intentionally EMPTY
        (event replay for them is a vectorized no-op).  Entities with a
        client or overridden hooks keep eagerly maintained sets."""
        if self._plain_aoi and self.aoi_slot >= 0 and self.space is not None:
            return self.space.derive_interests(self.aoi_slot)
        return self.interested_in

    def observers(self) -> Iterable["Entity"]:
        """Entities currently interested in this one (see neighbors)."""
        if self.aoi_slot >= 0 and self.space is not None \
                and self.space.aoi_enabled:
            return self.space.derive_observers(self.aoi_slot)
        return self.interested_by

    def _materialize_interests(self):
        """Promote lazily tracked interests into the eager sets -- called
        when a plain entity stops being plain (gains a client): the client
        needs create_entity ops and watcher counts for every current
        neighbor, so the packed state must surface."""
        if self.aoi_slot < 0 or self.space is None:
            return
        for other in self.space.derive_interests(self.aoi_slot):
            self.interested_in.add(other)
            other.interested_by.add(self)

    def _dematerialize_interests(self):
        """Inverse of _materialize_interests: the entity became plain again
        (lost its client); its eager sets would go stale because future
        events take the vectorized fast path, so drop them back into the
        packed-only representation."""
        if self.interested_in:
            for other in self.interested_in:
                other.interested_by.discard(self)
            self.interested_in.clear()

    # -- client binding ----------------------------------------------------
    def drop_client_ref(self):
        """Detach the client WITHOUT emitting client ops -- the connection is
        already gone (peer disconnect, duplicate-entity teardown).  Keeps the
        _watcher_clients bookkeeping consistent, which raw ``e.client = None``
        assignments would silently corrupt."""
        if self.client is None:
            return
        for other in self.interested_in:
            other._watcher_clients -= 1
            other._touch_watched()
        self.client = None
        self._touch_watched()
        self._recompute_plain()
        if self._plain_aoi:
            self._dematerialize_interests()

    def set_client(self, client: GameClient | None):
        was_plain = self._plain_aoi
        old = self.client
        if old is not None:
            old.destroy_entity(self)
            for other in self.interested_in:
                old.destroy_entity(other)
                other._watcher_clients -= 1
                other._touch_watched()
            self.client = None
            self._touch_watched()
            self.on_client_disconnected()
        if client is not None:
            if was_plain:
                # surface the packed interest state: the new client needs a
                # create op and a watcher count per current neighbor
                self._materialize_interests()
            for other in self.interested_in:
                other._watcher_clients += 1
                other._touch_watched()
            # flush pending deltas to the old audiences first -- the
            # snapshots below already contain them (see _interest)
            self._flush_attr_deltas()
            for other in self.interested_in:
                other._flush_attr_deltas()
            self.client = client
            self._touch_watched()
            client.create_entity(self, is_player=True)
            for other in self.interested_in:
                client.create_entity(other, is_player=False)
            self._recompute_plain()
            self.on_client_connected()
        else:
            self._recompute_plain()
            if self._plain_aoi:
                self._dematerialize_interests()

    def give_client_to(self, other: "Entity | str"):
        """Move client ownership to another entity -- local fast path, or
        cross-game by entity id through MT_GIVE_CLIENT_TO (reference:
        GiveClientTo, Entity.go:752-765; the client's gate switches its
        owner when the target's is_player create arrives,
        GateService.go:263-294)."""
        client = self.client
        if client is None:
            return
        target = other if isinstance(other, Entity) else (
            self.manager.entities.get(other))
        if target is not None:
            self.set_client(None)
            target.set_client(client)
            return
        game = self.game
        if game is None:
            raise KeyError(f"give_client_to: no local entity {other!r} "
                           "(not clustered)")
        game.give_client_to(self, other)

    # -- space movement ----------------------------------------------------
    def enter_space(self, space_id: str, pos: Vector3 | None = None):
        """Move to another space -- same-game fast path or cross-game
        migration when clustered (reference: EnterSpace, Entity.go:956-973)."""
        pos = pos or Vector3()
        rt = self._runtime()
        game = getattr(rt, "game", None)
        if game is not None:
            game.enter_space(self, space_id, pos)
            return
        sp = self.manager.spaces.get(space_id)
        if sp is None:
            raise KeyError(f"no local space {space_id} (not clustered)")
        if self.space is not None:
            self.space.leave_entity(self)
        sp.enter_entity(self, pos)

    # -- cluster conveniences ----------------------------------------------
    @property
    def game(self):
        """The hosting GameService when clustered, else None."""
        return getattr(self._runtime(), "game", None)

    @property
    def kvdb(self):
        """The game's KVDB service (None when not attached)."""
        game = self.game
        return getattr(game, "kvdb", None) if game is not None else None

    def call_entity(self, eid: str, method: str, *args):
        """Call a method on another entity by id (reference: goworld.Call /
        EntityManager.Call).  Clustered: the game routes (local fast path or
        dispatcher fabric); unclustered: local post only."""
        game = self.game
        if game is not None:
            game.call_entity(eid, method, *args)
            return
        local = self.manager.entities.get(eid)
        if local is None:
            raise KeyError(f"no local entity {eid} (not clustered)")
        self._runtime().post.post(lambda: local.call(method, *args))

    def set_filter_prop(self, key: str, value: str):
        """Set a gate-side filter property on this entity's client
        (reference: Entity.SetFilterProp, Entity.go:1136-1150)."""
        game = self.game
        if game is not None and self.client is not None:
            game.set_client_filter_prop(self, key, value)

    def call_filtered_clients(self, key: str, op: int, value: str,
                              method: str, *args):
        """Broadcast an RPC to every client whose filter props match
        (reference: Entity.CallFilteredClients, Entity.go:1150-1170)."""
        game = self.game
        if game is not None:
            game.call_filtered_clients(key, op, value, method, *args)

    # -- client calls ------------------------------------------------------
    def call_client(self, method: str, *args):
        if self.client is not None:
            self.client.call_client(self.id, method, args)

    def call_all_clients(self, method: str, *args):
        """Own client + every interested neighbor's client
        (reference: CallAllClients, Entity.go:743-748)."""
        self.call_client(method, *args)
        for other in self.interested_by:
            if other.client is not None:
                other.client.call_client(self.id, method, args)

    # -- timers ------------------------------------------------------------
    def add_callback(self, delay: float, method: str, *args) -> int:
        """One-shot timer; ``method`` is resolved on this entity so the timer
        survives migration/freeze by name (reference: Entity.go:271-311)."""
        tid = self._runtime().timers.add(
            delay, self._fire_timer, args=(method, args), pass_tid=True
        )
        self._timer_ids[tid] = (method, float(delay), False, args)
        return tid

    def add_timer(self, interval: float, method: str, *args) -> int:
        tid = self._runtime().timers.add(
            interval,
            self._fire_timer,
            repeat=True,
            interval=interval,
            args=(method, args),
            pass_tid=True,
        )
        self._timer_ids[tid] = (method, float(interval), True, args)
        return tid

    def cancel_timer(self, tid: int):
        self._timer_ids.pop(tid, None)
        self._runtime().timers.cancel(tid)

    def _fire_timer(self, tid: int, method: str, args: tuple):
        if self.destroyed:
            return
        rec = self._timer_ids.get(tid)
        if rec is not None and not rec[2]:
            # fired one-shots must not leak or re-fire after migration/restore
            del self._timer_ids[tid]
        getattr(self, method)(*args)

    def dump_timers(self) -> list:
        """Serializable timer state for migration/freeze.  Records the time
        *remaining* until next fire so the timer keeps its phase on the
        destination (reference behavior: restore by FireTime - now,
        Entity.go:349-390).  Record: [method, interval, repeat, args, remaining]."""
        timers = self._runtime().timers
        out = []
        for tid, (method, interval, repeat, args) in self._timer_ids.items():
            remaining = timers.remaining(tid)
            if remaining is None:
                continue
            out.append([method, interval, repeat, args, remaining])
        return out

    def restore_timers(self, dumped: list):
        for method, interval, repeat, args, remaining in dumped:
            if repeat:
                tid = self._runtime().timers.add(
                    remaining,
                    self._fire_timer,
                    repeat=True,
                    interval=interval,
                    args=(method, tuple(args)),
                    pass_tid=True,
                )
                self._timer_ids[tid] = (method, float(interval), True, tuple(args))
            else:
                tid = self._runtime().timers.add(
                    remaining,
                    self._fire_timer,
                    args=(method, tuple(args)),
                    pass_tid=True,
                )
                self._timer_ids[tid] = (method, float(interval), False, tuple(args))

    # -- RPC ---------------------------------------------------------------
    def call(self, method: str, *args):
        """In-process direct dispatch (the local fast path; remote routing is
        the dispatcher fabric's job -- reference EntityManager.go:429-442)."""
        desc = self.desc.rpc_descs.get(method) if self.desc else None
        if desc is None:
            raise AttributeError(f"{self.type_name} has no RPC {method!r}")
        return desc.func(self, *args)

    def on_call_from_client(self, method: str, args: tuple, client_id: str):
        from .rpc import may_call

        desc = self.desc.rpc_descs.get(method) if self.desc else None
        if desc is None:
            raise AttributeError(f"{self.type_name} has no RPC {method!r}")
        is_owner = self.client is not None and self.client.client_id == client_id
        if not may_call(desc, from_client=True, is_owner=is_owner):
            raise PermissionError(
                f"client {client_id} may not call {self.type_name}.{method}"
            )
        if not desc.arity_ok(len(args)):
            # reject malformed client input at the wire boundary, not inside
            # entity logic
            raise TypeError(
                f"{self.type_name}.{method} expects "
                f"{desc.min_args}..{desc.max_args} args, got {len(args)}"
            )
        return desc.func(self, *args)

    # -- migration / freeze data ------------------------------------------
    def migrate_data(self) -> dict:
        """Full state snapshot for EnterSpace migration and freeze/restore
        (reference: entityMigrateData, Entity.go:78-89,631-651)."""
        return {
            "type": self.type_name,
            "id": self.id,
            "attrs": self.attrs.to_dict(),
            "pos": self.position.to_tuple(),
            "yaw": self.yaw,
            "timers": self.dump_timers(),
            "client": (
                (self.client.client_id, self.client.gate_id)
                if self.client
                else None
            ),
            "client_syncing": self.client_syncing,
            "space_id": self.space.id if self.space else None,
        }

    # -- destroy -----------------------------------------------------------
    def destroy(self):
        if self.destroyed:
            return
        self._destroy_impl(is_migrate=False)

    def _destroy_impl(self, is_migrate: bool):
        self.destroyed = True
        if self.space is not None:
            self.space.leave_entity(self)
        if not is_migrate:
            if self.persistent:
                self.destroyed = False  # save() guards on destroyed
                self.save()
                self.destroyed = True
            self.on_destroy()
            if self.client is not None:
                self.client.destroy_entity(self)
                self.client = None
        for tid in list(self._timer_ids):
            self._runtime().timers.cancel(tid)
        self._timer_ids.clear()
        if self.manager is not None:
            self.manager._on_entity_destroyed(self)

    def _runtime(self):
        return self.manager.runtime
