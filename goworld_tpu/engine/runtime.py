"""The game runtime: single logic thread + batched tick phases.

Mirrors the reference's game main loop (GameService.serveRoutine,
/root/reference/components/game/GameService.go:88-192): one thread runs all
entity logic; each tick fires timers, executes the batched AOI pass, flushes
client-bound traffic, and drains the post queue.  Networking components wrap
this object (components/game); tests drive it directly.

Tick phases (order matters and is part of the engine contract):

  1. timers        -- user logic (AI moves, scheduled callbacks);
  2. AOI           -- submit dirty spaces, one batched TPU step per bucket,
                      replay enter/leave events through entity hooks;
  3. sync          -- collect position/yaw records for every entity flagged
                      dirty (reference: CollectEntitySyncInfos,
                      Entity.go:1221-1267) and flush attr deltas;
  4. post          -- callbacks queued by workers/IO during the tick.
"""

from __future__ import annotations

import time
from typing import Callable

from ..utils.crontab import Crontab
from .aoi import AOIEngine
from .entity import SYNC_NEIGHBORS, SYNC_OWN, Entity
from .manager import EntityManager
from .post import PostQueue
from .timers import TimerQueue


class Runtime:
    def __init__(
        self,
        aoi_backend: str = "cpu",
        now: Callable[[], float] = time.monotonic,
        on_error: Callable[[BaseException], None] | None = None,
    ):
        self.now = now
        self.on_error = on_error or self._default_on_error
        self.timers = TimerQueue(now)
        self.post = PostQueue()
        self.crontab = Crontab()
        self.aoi = AOIEngine(default_backend=aoi_backend)
        self.entities = EntityManager(self)
        self.tick_count = 0
        # entities with pending sync flags / attr deltas / quiet countdowns;
        # the sync phase walks ONLY these (reference scans every entity each
        # tick -- Entity.go:1221-1267 -- which compiled Go affords)
        self._dirty_entities: set[Entity] = set()
        # position sync records collected this tick:
        # (client_id, gate_id, entity_id, x, y, z, yaw)
        self.sync_out: list[tuple] = []
        # optional hooks set by the hosting component (GameService): called
        # when entities register/unregister so the dispatcher directory stays
        # current (reference: MT_NOTIFY_CREATE_ENTITY/DESTROY)
        self.on_entity_registered = None
        self.on_entity_unregistered = None
        # set by GameService when clustered; entities reach cluster ops
        # (enter_space migration, remote calls) through it
        self.game = None

    def _default_on_error(self, e: BaseException):
        import traceback

        traceback.print_exception(type(e), e, e.__traceback__)

    # -- the tick ----------------------------------------------------------
    def tick(self):
        self.tick_count += 1
        self.timers.tick(self.on_error)
        self.crontab.maybe_check()
        self._aoi_phase()
        self._sync_phase()
        self.post.tick(self.on_error)

    def _aoi_phase(self):
        spaces = list(self.entities.spaces.values())
        staged = [sp for sp in spaces if sp.submit_aoi()]
        if staged:
            self.aoi.flush()
            for sp in staged:
                sp.dispatch_aoi_events()

    def _sync_phase(self):
        """Collect position sync + flush attr deltas for DIRTY entities only
        (entities self-register via Entity._mark_dirty; idle entities cost
        nothing per tick)."""
        if not self._dirty_entities:
            return
        dirty, self._dirty_entities = self._dirty_entities, set()
        for e in dirty:
            if e.destroyed:
                continue
            if e._sync_flags:
                self._collect_sync(e)
                e._sync_flags = 0
            if e._attr_deltas:
                e._flush_attr_deltas()

    def _collect_sync(self, e: Entity):
        """One 16-byte-payload record per flagged entity per tick
        (reference record layout: proto.go:135-139)."""
        flags = e._sync_flags
        x, y, z = e.position.to_tuple()
        if flags & SYNC_OWN and e.client is not None:
            self.sync_out.append(
                (e.client.client_id, e.client.gate_id, e.id, x, y, z, e.yaw)
            )
        if flags & SYNC_NEIGHBORS and e._watcher_clients > 0:
            for other in e.interested_by:
                if other.client is not None:
                    self.sync_out.append(
                        (
                            other.client.client_id,
                            other.client.gate_id,
                            e.id,
                            x,
                            y,
                            z,
                            e.yaw,
                        )
                    )

    def drain_sync(self) -> list[tuple]:
        out = self.sync_out
        self.sync_out = []
        return out
