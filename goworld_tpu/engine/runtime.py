"""The game runtime: single logic thread + batched tick phases.

Mirrors the reference's game main loop (GameService.serveRoutine,
/root/reference/components/game/GameService.go:88-192): one thread runs all
entity logic; each tick fires timers, executes the batched AOI pass, flushes
client-bound traffic, and drains the post queue.  Networking components wrap
this object (components/game); tests drive it directly.

Tick phases (order matters and is part of the engine contract):

  1. timers        -- user logic (AI moves, scheduled callbacks);
  2. AOI           -- submit dirty spaces, one batched TPU step per bucket,
                      replay enter/leave events through entity hooks;
  3. sync          -- collect position/yaw records for every entity flagged
                      dirty (reference: CollectEntitySyncInfos,
                      Entity.go:1221-1267) and flush attr deltas;
  4. post          -- callbacks queued by workers/IO during the tick.
"""

from __future__ import annotations

import os
import time
from typing import Callable

from .. import faults, telemetry
from ..telemetry import trace as _trace
from ..utils.crontab import Crontab
from .aoi import AOIEngine
from .entity import SYNC_NEIGHBORS, SYNC_OWN, Entity
from .manager import EntityManager
from .placement import PlacementController
from .post import PostQueue
from .timers import TimerQueue

# whole-tick latency histogram (pow2 buckets -> p50/p99 at /debug/metrics);
# a no-op while telemetry is disabled
_TICK_SECONDS = telemetry.histogram(
    "tick.seconds", "whole-tick wall time (timers+aoi+sync+post)")

# SLO gate: a tick over this budget trips the flight recorder (0 = off).
# Env-configured -- the budget is an ops knob, not an engine parameter.
try:
    _TICK_BUDGET_MS = float(os.environ.get("GW_TICK_BUDGET_MS", "0") or 0)
except ValueError:
    _TICK_BUDGET_MS = 0.0


class Runtime:
    def __init__(
        self,
        aoi_backend: str = "cpu",
        now: Callable[[], float] = time.monotonic,
        on_error: Callable[[BaseException], None] | None = None,
        aoi_mesh=None,
        aoi_pipeline: bool = False,
        aoi_delta_staging: bool = True,
        aoi_tpu_min_capacity: int = 4096,
        aoi_rowshard_min_capacity: int = 65536,
        aoi_flush_sched: bool = True,
        aoi_emit: str = "auto",
        aoi_paged: bool = False,
        aoi_cross_tick: bool = False,
        aoi_fused: bool = False,
        aoi_interest: str = "device",
        aoi_placement: str = "static",
        aoi_migration_threshold_ms: float = 5.0,
        aoi_migration_cooldown: int = 64,
        aoi_cohort=False,
        aoi_cohort_ladder=None,
        aoi_cohort_planner: str = "static",
        aoi_cohort_hot_ms: float = 8.0,
        aoi_cohort_churn_budget: int = 2,
        aoi_cohort_cooldown: int = 32,
        aoi_checkpoint: str = "off",
        aoi_checkpoint_interval: int = 16,
        aoi_checkpoint_dir: str | None = None,
        aoi_checkpoint_store=None,
        aoi_checkpoint_kvdb=None,
        fault_plan: "faults.FaultPlan | str | None" = None,
        telemetry_on: bool = False,
    ):
        # Install BEFORE AOIEngine construction: buckets decide at __init__
        # whether to keep eager host mirrors (faults.active()).
        if fault_plan is not None:
            faults.install(fault_plan)
        # The injectable clock doubles as the span clock (docs/
        # observability.md): enabling telemetry through the Runtime routes
        # every span timestamp through ``now``, so tests drive tracing
        # deterministically.  False leaves process-global state untouched
        # (another component may have enabled it already).
        if telemetry_on:
            telemetry.enable(clock=now)
        self.now = now
        self.on_error = on_error or self._default_on_error
        self.timers = TimerQueue(now)
        self.post = PostQueue()
        self.crontab = Crontab()
        self.aoi = AOIEngine(default_backend=aoi_backend, mesh=aoi_mesh,
                             pipeline=aoi_pipeline,
                             delta_staging=aoi_delta_staging,
                             tpu_min_capacity=aoi_tpu_min_capacity,
                             rowshard_min_capacity=aoi_rowshard_min_capacity,
                             flush_sched=aoi_flush_sched, emit=aoi_emit,
                             paged=aoi_paged, cross_tick=aoi_cross_tick,
                             fused=aoi_fused,
                             interest_mode=aoi_interest,
                             cohort=aoi_cohort,
                             cohort_ladder=aoi_cohort_ladder)
        # telemetry-driven placement (engine/placement.py): "static" keeps
        # spaces where capacity routing put them (migrate() stays available
        # as the operator entry point); "auto" re-homes hot/idle spaces
        # between tiers live, one at a time, from per-bucket load scores
        self.placement = PlacementController(
            self.aoi, mode=aoi_placement,
            threshold_ms=aoi_migration_threshold_ms,
            cooldown_ticks=aoi_migration_cooldown)
        # cohort membership planner (engine/placement.py CohortPlanner):
        # only meaningful with aoi_cohort on; "auto" re-buckets stacked
        # vs solo spaces live from the same load scores, under a churn
        # budget, and doubles as the aoi.cohort demotion re-arm loop
        self.cohort_planner = None
        if aoi_cohort:
            from .placement import CohortPlanner

            self.cohort_planner = CohortPlanner(
                self.aoi, mode=aoi_cohort_planner,
                hot_ms=aoi_cohort_hot_ms,
                churn_budget=aoi_cohort_churn_budget,
                cooldown_ticks=aoi_cohort_cooldown)
        # durable world state (engine/checkpoint.py): "off" costs nothing;
        # "interval"/"continuous" stream per-space incremental checkpoints
        # off the hot path.  Backends come pre-built (aoi_checkpoint_store/
        # _kvdb -- the GameService path, via storage/kvdb config) or are
        # filesystem defaults under aoi_checkpoint_dir
        self.checkpoint = None
        if aoi_checkpoint != "off":
            if aoi_checkpoint_store is None or aoi_checkpoint_kvdb is None:
                if aoi_checkpoint_dir is None:
                    raise ValueError(
                        "aoi_checkpoint=%r needs aoi_checkpoint_dir or "
                        "pre-built store+kvdb backends" % aoi_checkpoint)
                from .checkpoint import _open_backends
                aoi_checkpoint_store, aoi_checkpoint_kvdb = \
                    _open_backends(aoi_checkpoint_dir)
            self.arm_checkpoints(aoi_checkpoint_store, aoi_checkpoint_kvdb,
                                 mode=aoi_checkpoint,
                                 interval=aoi_checkpoint_interval)
        self.entities = EntityManager(self)
        self.tick_count = 0
        # entities with pending sync flags / attr deltas / quiet countdowns;
        # the sync phase walks ONLY these (reference scans every entity each
        # tick -- Entity.go:1221-1267 -- which compiled Go affords)
        self._dirty_entities: set[Entity] = set()
        # spaces whose sync COLUMN holds pending flags (vectorized ingest
        # writes -- engine/ecs.py): drained at the head of the sync phase
        # into the per-entity dirty machinery.  A dict used as an ordered
        # set: drain order stays insertion order (deterministic)
        self._col_sync_spaces: dict = {}
        # position sync records collected this tick:
        # (client_id, gate_id, entity_id, x, y, z, yaw)
        self.sync_out: list[tuple] = []
        # optional hooks set by the hosting component (GameService): called
        # when entities register/unregister so the dispatcher directory stays
        # current (reference: MT_NOTIFY_CREATE_ENTITY/DESTROY)
        self.on_entity_registered = None
        self.on_entity_unregistered = None
        # set by GameService when clustered; entities reach cluster ops
        # (enter_space migration, remote calls) through it
        self.game = None

    def arm_checkpoints(self, store, manifest, mode: str = "interval",
                        interval: int = 16, **kw):
        """Attach (or replace) the checkpoint controller post-construction
        -- the GameService path, after storage/kvdb backends exist."""
        from .checkpoint import CheckpointController

        if self.checkpoint is not None:
            self.checkpoint.close()
        self.checkpoint = CheckpointController(
            self.aoi, store, manifest, mode=mode, interval=interval, **kw)
        return self.checkpoint

    def _default_on_error(self, e: BaseException):
        import traceback

        traceback.print_exception(type(e), e, e.__traceback__)

    # -- the tick ----------------------------------------------------------
    def tick(self):
        self.tick_count += 1
        _trace.mark_tick(self.tick_count)
        _t0 = _trace.t()
        _wall0 = time.perf_counter() if _TICK_BUDGET_MS > 0 else 0.0
        with _trace.span("tick.timers"):
            self.timers.tick(self.on_error)
            self.crontab.maybe_check()
        with _trace.span("tick.aoi"):
            self._aoi_phase()
        with _trace.span("tick.sync"):
            self._sync_phase()
        with _trace.span("tick.post"):
            self.post.tick(self.on_error)
        # placement decisions AFTER the tick's phases: scores reflect the
        # flush that just ran, and a migration started here snapshots
        # between ticks (no partially-staged state)
        self.placement.step()
        if self.cohort_planner is not None:
            # same between-tick discipline as placement: join/leave move
            # snapshots only after this tick's events are delivered
            self.cohort_planner.step()
        # checkpoint capture AFTER placement: events for this tick are
        # delivered, migrations are settled, so the export is snapshot-
        # consistent; the expensive half runs on the background writer
        if self.checkpoint is not None:
            self.checkpoint.sync_tracked({
                sid: sp._aoi_handle
                for sid, sp in self.entities.spaces.items()
                if sp._aoi_handle is not None})
            self.checkpoint.step(self.tick_count)
        _TICK_SECONDS.observe(_trace.lap("tick", _t0))
        if _TICK_BUDGET_MS > 0:
            _dur_ms = (time.perf_counter() - _wall0) * 1000.0
            if _dur_ms > _TICK_BUDGET_MS:
                from ..telemetry import flight as _flight

                _flight.slo_breach(self.tick_count, _dur_ms,
                                   _TICK_BUDGET_MS)

    def _aoi_phase(self):
        spaces = list(self.entities.spaces.values())
        staged = False
        for sp in spaces:
            staged = sp.submit_aoi() or staged
        # a pipelined bucket may hold an inflight tick even when nothing new
        # is staged (trailing flush); events can land on any AOI space, not
        # just the ones staged this tick
        if staged or self.aoi.has_pending():
            # the flush span nests aoi.dispatch + aoi.harvest (the split-
            # phase scheduler, docs/perf.md): dispatch of EVERY bucket
            # precedes the first blocking fetch
            with _trace.span("aoi.flush"):
                self.aoi.flush()
            with _trace.span("aoi.emit"):
                for sp in spaces:
                    sp.dispatch_aoi_events()
        # slots freed last tick become reusable only NOW, after event
        # delivery: with a pipelined calculator, events replayed this phase
        # may reference a slot freed last tick, and recycling before the
        # replay would let an entity created inside an on_leave_aoi hook
        # take the slot and inherit the dead occupant's pending enter pairs
        for sp in spaces:
            sp.recycle_aoi_slots()

    def _sync_phase(self):
        """Collect position sync + flush attr deltas for DIRTY entities only
        (entities self-register via Entity._mark_dirty; idle entities cost
        nothing per tick).  The dirty set object is STABLE -- entities cache
        a reference to it (Entity._dirty_set) -- so it is drained in place,
        never swapped.  The common steady-state case (no client, nobody's
        client watching) exits after two integer tests."""
        # fold pending sync-column flags (batched ingest) into the dirty
        # machinery first, so batched and per-entity movement emit through
        # one path -- exactly-once per entity per tick
        css = self._col_sync_spaces
        if css:
            for sp in css:
                sp.drain_column_sync()
            css.clear()
        ds = self._dirty_entities
        if not ds:
            return
        dirty = list(ds)
        ds.clear()
        for e in dirty:
            if e.destroyed:
                continue
            flags = e._sync_flags
            if flags:
                e._sync_flags = 0
                if (e.client is not None or
                        (flags & SYNC_NEIGHBORS and e._watcher_clients > 0)):
                    self._collect_sync(e, flags)
            if e._attr_deltas:
                e._flush_attr_deltas()

    def _collect_sync(self, e: Entity, flags: int):
        """One 16-byte-payload record per flagged entity per tick
        (reference record layout: proto.go:135-139)."""
        x, y, z = e.position.to_tuple()
        if flags & SYNC_OWN and e.client is not None:
            self.sync_out.append(
                (e.client.client_id, e.client.gate_id, e.id, x, y, z, e.yaw)
            )
        if flags & SYNC_NEIGHBORS and e._watcher_clients > 0:
            for other in e.interested_by:
                if other.client is not None:
                    self.sync_out.append(
                        (
                            other.client.client_id,
                            other.client.gate_id,
                            e.id,
                            x,
                            y,
                            z,
                            e.yaw,
                        )
                    )

    def drain_sync(self) -> list[tuple]:
        out = self.sync_out
        self.sync_out = []
        return out
