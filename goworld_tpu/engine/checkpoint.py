"""Durable world state: async snapshot-consistent incremental checkpoints.

Until this module, every byte of world state lived in process memory: the
``storage/`` and ``kvdb/`` backends were wired to nothing, so a game-process
crash lost every space -- the one failure mode the fault seams, live
migration, and chip-loss evacuation (docs/robustness.md) could not heal.
The :class:`CheckpointController` closes that hole by reusing the migration
machinery as a persistence engine (ROADMAP open item: durable state):

* **Base image** = the live-migration wire format, verbatim.  A space's
  checkpoint base is ``bucket.export_snapshot(slot)`` -- the delta-staging
  ``ops/aoi_stage.pad_packet`` packet plus the packed previous-tick
  interest words -- already a consistent image with no tick stall (the
  export drains any pipelined in-flight tick first, the same double-cover
  alignment live migration relies on).
* **Deltas** ride the same two wire formats the hot path already uses:
  positions as a ``pad_packet`` (row, col, x, z) packet over the
  bit-pattern-changed columns (PR 2's H2D delta format doubles as the
  journal delta format), and interest state as dirty PAGES of the packed
  words matrix (:data:`PAGE_ROWS` rows per page -- PR 8's page granularity
  reused at the durability layer).  A tick that moved 1% of a space
  journals ~1% of its bytes.
* **Off the hot path**: ``step()`` captures (cheap numpy diffs against the
  last-checkpointed shadow, between ticks, snapshot-consistent by
  construction) and enqueues; a background writer thread serializes,
  CRC-stamps, retries, and lands records in any ``storage/backends.py``
  backend.  The bounded queue never blocks the tick: when it is full the
  capture is dropped, counted, and the next capture is forced to a fresh
  base so the delta chain self-heals (``ckpt.backlog`` gauge + drop
  counter make the pressure visible).
* **Manifest**: one monotonic ``(space, epoch, tick)`` entry per durable
  epoch in a ``kvdb/`` backend, written only AFTER the journal record.
  Records are self-verifying (per-record CRC over the msgpack blob), so a
  torn write -- process killed mid-``os.replace``, a ``store.write``
  ``partial`` fault, a poisoned blob -- is detected at restore and the
  chain falls back to the last consistent epoch.
* **Crash-restart = import_snapshot + delta replay + dispatcher bounded
  replay.**  ``restore()`` walks the manifest newest-first for the longest
  fully-CRC-valid base+delta chain, folds it into a migration snapshot,
  and ``restore_into()`` replays it onto a fresh bucket slot through the
  exact ``import_snapshot`` path chip-loss evacuation uses.  The restored
  process re-registers with the dispatcher and the existing exactly-once
  salvage->register->replay reconnect path (dispatchercluster) delivers
  the gap -- the same exactly-once argument as evacuation, extended
  across a process boundary.  ``python -m goworld_tpu.engine.checkpoint``
  is the deterministic crash-restart driver the restart bench/smoke/tests
  build on (run -> SIGKILL mid-tick -> restore -> replay, per-tick event
  CRCs journaled line-buffered so the parent can prove ``events_lost=0``).

Fault seams (``store.write`` / ``store.read`` / ``store.manifest``):
fail/oom/reset -> counted retry with capped backoff; stall -> absorbed by
the writer thread; partial -> a torn record lands (caught by CRC at
restore); poison -> a corrupt blob lands (same).  All self-healing and
re-armable -- an exhausted retry budget abandons that epoch (counted),
never the controller.

Telemetry (docs/observability.md): spans ``ckpt.snapshot`` / ``ckpt.delta``
/ ``ckpt.flush`` / ``ckpt.restore``; counters ``ckpt.bytes`` /
``ckpt.records`` / ``ckpt.epochs`` / ``ckpt.retries`` / ``ckpt.torn``;
gauges ``ckpt.backlog`` / ``ckpt.lag_ticks``.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import zlib

import numpy as np

from .. import faults, telemetry
from ..telemetry import trace as _T
from .aoi import _build_snapshot, _unpack_positions

# rows per dirty page of the packed interest-words matrix.  Matches the
# paged-storage grain (ops/aoi_pages.PAGE_WORDS): a page is the unit the
# device path already thinks in, so dirty tracking composes with it.
PAGE_ROWS = 64

# storage namespace for journal records; eid = "<space>.<epoch:08d>"
RECORD_TYPE = "__ckpt__"
# kvdb manifest key = "ckpt/<space>/<epoch:08d>" -> json {epoch,tick,kind,crc}
MANIFEST_PREFIX = "ckpt/"
# any printable byte above the digits: the half-open find() upper bound
_MANIFEST_END = "~"

_BYTES = telemetry.counter(
    "ckpt.bytes", "journal bytes handed to the storage backend")
_RECORDS = telemetry.counter(
    "ckpt.records", "checkpoint journal records durably written")
_EPOCHS = telemetry.counter(
    "ckpt.epochs", "checkpoint epochs whose manifest entry landed")
_RETRIES = telemetry.counter(
    "ckpt.retries", "store.* operations retried after an injected or real "
    "backend fault")
_TORN = telemetry.counter(
    "ckpt.torn", "torn/corrupt journal records detected (CRC or decode "
    "mismatch at restore)")
_BACKLOG = telemetry.gauge(
    "ckpt.backlog", "captures queued to the background checkpoint writer")
_LAG = telemetry.gauge(
    "ckpt.lag_ticks", "worst tracked space's enqueued-tick minus durable-"
    "tick gap (ticks of checkpoint work still in flight)")


def _crc(blob: bytes) -> int:
    return zlib.crc32(blob) & 0xFFFFFFFF


def _record_eid(space_id: str, epoch: int) -> str:
    return f"{space_id}.{epoch:08d}"


def _manifest_key(space_id: str, epoch: int) -> str:
    return f"{MANIFEST_PREFIX}{space_id}/{epoch:08d}"


def _pos_packet(cols: np.ndarray, x: np.ndarray, z: np.ndarray):
    """Serialize changed position columns through the delta-staging wire
    format (ops/aoi_stage.pad_packet, page-granular padding -- <= 1 page
    of duplicated-tail waste; the replay scatter is an assignment, which
    absorbs the duplicates idempotently)."""
    from ..ops import aoi_stage as AS

    if not len(cols):
        return None
    rows, pc, px, pz = (np.asarray(a) for a in AS.pad_packet(
        np.zeros(len(cols), np.int64), cols.astype(np.int64),
        x.astype(np.float32), z.astype(np.float32), page_granular=True))
    return {"n": int(len(pc)), "rows": rows.astype(np.int64).tobytes(),
            "cols": pc.astype(np.int64).tobytes(),
            "xv": px.astype(np.float32).tobytes(),
            "zv": pz.astype(np.float32).tobytes()}


def _apply_pos_packet(pkt, x: np.ndarray, z: np.ndarray) -> None:
    if pkt is None:
        return
    cols = np.frombuffer(pkt["cols"], np.int64)
    x[cols] = np.frombuffer(pkt["xv"], np.float32)
    z[cols] = np.frombuffer(pkt["zv"], np.float32)


class _SpaceShadow:
    """Per-tracked-space last-checkpointed state: the diff baseline the
    next delta is computed against, plus the epoch chain bookkeeping."""

    __slots__ = ("handle", "capacity", "x", "z", "r", "act", "sub", "words",
                 "epoch", "deltas_since_base", "force_base",
                 "enqueued_tick", "acked_tick", "acked_epoch")

    def __init__(self, handle):
        self.handle = handle
        self.capacity = handle.capacity
        self.x = self.z = self.r = self.act = self.words = None
        self.sub = True
        self.epoch = 0
        self.deltas_since_base = 0
        self.force_base = True
        self.enqueued_tick = 0
        self.acked_tick = 0
        self.acked_epoch = -1


class CheckpointController:
    """Streams per-space incremental checkpoints off the hot path.

    ``mode``: ``"off"`` (step() is a no-op), ``"interval"`` (capture every
    ``interval`` ticks), ``"continuous"`` (every tick).  ``full_every``
    bounds the delta chain: after that many deltas the next capture is a
    fresh base, so restore replay work -- and the blast radius of one torn
    record -- stays bounded.
    """

    def __init__(self, engine, store, manifest, mode: str = "interval",
                 interval: int = 16, full_every: int = 64,
                 queue_max: int = 256, max_retries: int = 5,
                 retry_base_s: float = 0.001):
        if mode not in ("off", "interval", "continuous"):
            raise ValueError(f"unknown checkpoint mode {mode!r}")
        if interval < 1:
            raise ValueError("checkpoint interval must be >= 1")
        self.engine = engine
        self.store = store
        self.manifest = manifest
        self.mode = mode
        self.interval = interval
        self.full_every = full_every
        self.max_retries = max_retries
        self.retry_base_s = retry_base_s
        self._shadows: dict[str, _SpaceShadow] = {}
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=queue_max)
        self._lock = threading.Lock()
        self.stats = {"captures": 0, "bases": 0, "deltas": 0,
                      "skipped_empty": 0, "backlog_drops": 0,
                      "write_retries": 0, "manifest_retries": 0,
                      "read_retries": 0, "dropped_epochs": 0,
                      "torn_records": 0, "bytes_written": 0,
                      "records_written": 0, "restores": 0}
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._writer = None
        if mode != "off":
            self._writer = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._writer.start()

    # -- tracking ---------------------------------------------------------

    def track(self, space_id: str, handle) -> None:
        """Start (or re-point) checkpointing for one space.  Idempotent;
        a changed handle object or capacity (space growth re-homes the
        slot) forces the next capture to a fresh base."""
        sh = self._shadows.get(space_id)
        if sh is not None and sh.handle is handle \
                and sh.capacity == handle.capacity:
            return
        if sh is not None and sh.handle is not handle:
            nsh = _SpaceShadow(handle)
            nsh.epoch = sh.epoch  # keep the manifest chain monotonic
            nsh.enqueued_tick = sh.enqueued_tick
            nsh.acked_tick, nsh.acked_epoch = sh.acked_tick, sh.acked_epoch
            self._shadows[space_id] = nsh
            return
        self._shadows[space_id] = _SpaceShadow(handle)

    def untrack(self, space_id: str) -> None:
        self._shadows.pop(space_id, None)

    def sync_tracked(self, live: dict) -> None:
        """Reconcile the tracked set against ``{space_id: handle}`` --
        the Runtime's per-tick glue (spaces come and go; growth swaps
        handles)."""
        for sid, h in live.items():
            self.track(sid, h)
        for sid in [s for s in self._shadows if s not in live]:
            self.untrack(sid)

    # -- capture (the tick-side half) -------------------------------------

    def step(self, tick: int) -> None:
        """Capture every due space.  Runs between ticks (after event
        delivery), so the export is snapshot-consistent by construction;
        the expensive half (serialize + write) happens on the writer."""
        if self.mode == "off":
            return
        if self.mode == "interval" and tick % self.interval != 0:
            return
        for sid in sorted(self._shadows):
            self.capture(sid, tick)
        self._update_lag()

    def capture(self, space_id: str, tick: int) -> bool:
        """Capture one space now (used directly by benches/tests; step()
        calls it on cadence).  Returns True when a record was enqueued."""
        sh = self._shadows[space_id]
        h = sh.handle
        if h.released:
            return False
        self.stats["captures"] += 1
        with _T.span("ckpt.snapshot"):
            snap = h.bucket.export_snapshot(h.slot)
            x, z = _unpack_positions(snap)
        if sh.force_base or sh.x is None or sh.capacity != snap["capacity"] \
                or sh.deltas_since_base >= self.full_every \
                or sh.words.shape != snap["words"].shape:
            kind, payload = "base", self._base_payload(snap)
            self.stats["bases"] += 1
        else:
            with _T.span("ckpt.delta"):
                payload = self._delta_payload(sh, snap, x, z)
            if payload is None:
                self.stats["skipped_empty"] += 1
                return False
            kind = "delta"
            self.stats["deltas"] += 1
        payload.update({"kind": kind, "space": space_id,
                        "epoch": sh.epoch, "tick": tick,
                        "capacity": int(snap["capacity"]),
                        "sub": bool(snap["sub"])})
        stack = getattr(h, "_policy_stack", None)
        if stack is not None:
            # interest-policy state rides EVERY record (base and delta) as
            # a self-contained blob in the pad_packet snapshot format:
            # last-wins at fold time, so the chain walk needs no
            # stack-specific delta logic
            payload["interest"] = stack.export_payload()
        try:
            self._q.put_nowait((space_id, sh.epoch, tick, kind, payload))
        except queue.Full:
            # never block the tick: drop the capture, force the next one
            # to a base so the delta chain stays consistent
            self.stats["backlog_drops"] += 1
            sh.force_base = True
            return False
        self._idle.clear()
        _BACKLOG.set(self._q.qsize())
        # the shadow becomes the new diff baseline ONLY for enqueued work
        sh.x, sh.z = x, z
        sh.r = snap["r"]
        sh.act = snap["act"]
        sh.sub = bool(snap["sub"])
        sh.words = snap["words"]
        sh.capacity = int(snap["capacity"])
        sh.epoch += 1
        sh.enqueued_tick = tick
        sh.deltas_since_base = 0 if kind == "base" else \
            sh.deltas_since_base + 1
        sh.force_base = False
        return True

    @staticmethod
    def _base_payload(snap: dict) -> dict:
        pkt = snap["packet"]
        payload = {"packet": None, "r": snap["r"].tobytes(),
                   "act": np.asarray(snap["act"], bool).tobytes(),
                   "words": snap["words"].tobytes(),
                   "words_cols": int(snap["words"].shape[1])}
        if pkt is not None:
            rows, cols, xv, zv = (np.asarray(a) for a in pkt)
            payload["packet"] = {
                "n": int(len(cols)),
                "rows": rows.astype(np.int64).tobytes(),
                "cols": cols.astype(np.int64).tobytes(),
                "xv": xv.astype(np.float32).tobytes(),
                "zv": zv.astype(np.float32).tobytes()}
        return payload

    def _delta_payload(self, sh: _SpaceShadow, snap: dict,
                       x: np.ndarray, z: np.ndarray) -> dict | None:
        """Dirty-column / dirty-page diff against the shadow.  Bit-pattern
        compares (uint32 views), the delta-staging convention: -0.0 vs 0.0
        is a change, NaNs compare stably."""
        pos_chg = np.nonzero(
            (x.view(np.uint32) != sh.x.view(np.uint32))
            | (z.view(np.uint32) != sh.z.view(np.uint32)))[0]
        r = snap["r"]
        act = np.asarray(snap["act"], bool)
        r_chg = np.nonzero(r.view(np.uint32) != sh.r.view(np.uint32))[0]
        a_chg = np.nonzero(act != sh.act)[0]
        words = snap["words"]
        row_dirty = np.any(words != sh.words, axis=1)
        pages = {}
        if row_dirty.any():
            dirty_pages = np.nonzero(
                np.add.reduceat(
                    row_dirty,
                    np.arange(0, len(row_dirty), PAGE_ROWS)) > 0)[0]
            for p in dirty_pages.tolist():
                pages[str(p)] = words[p * PAGE_ROWS:(p + 1) * PAGE_ROWS] \
                    .tobytes()
        sub_chg = bool(snap["sub"]) != sh.sub
        if not len(pos_chg) and not len(r_chg) and not len(a_chg) \
                and not pages and not sub_chg:
            return None
        payload = {"pos": _pos_packet(pos_chg, x[pos_chg], z[pos_chg]),
                   "pages": pages, "words_cols": int(words.shape[1])}
        if len(r_chg):
            payload["r_idx"] = r_chg.astype(np.int64).tobytes()
            payload["r_val"] = r[r_chg].tobytes()
        if len(a_chg):
            payload["act_idx"] = a_chg.astype(np.int64).tobytes()
            payload["act_val"] = act[a_chg].tobytes()
        return payload

    def _update_lag(self) -> None:
        lag = 0
        for sh in self._shadows.values():
            lag = max(lag, sh.enqueued_tick - sh.acked_tick)
        _LAG.set(lag)

    # -- the background writer --------------------------------------------

    def _writer_loop(self) -> None:
        import msgpack

        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                self._idle.set()
                continue
            _BACKLOG.set(self._q.qsize())
            sid, epoch, tick, kind, payload = item
            with _T.span("ckpt.flush"):
                blob = msgpack.packb(payload, use_bin_type=True)
                record = {"crc": _crc(blob), "epoch": epoch, "tick": tick,
                          "kind": kind, "blob": blob}
                ok = self._guarded_write(_record_eid(sid, epoch), record)
                if ok:
                    ok = self._guarded_manifest_put(sid, epoch, tick, kind,
                                                    record["crc"], len(blob))
            if ok:
                self.stats["records_written"] += 1
                self.stats["bytes_written"] += len(blob)
                _RECORDS.inc()
                _BYTES.inc(len(blob))
                _EPOCHS.inc()
                sh = self._shadows.get(sid)
                if sh is not None and epoch > sh.acked_epoch:
                    sh.acked_epoch, sh.acked_tick = epoch, tick
            else:
                # epoch abandoned: the chain above it is unusable, so the
                # next capture must restart from a base (self-healing)
                self.stats["dropped_epochs"] += 1
                sh = self._shadows.get(sid)
                if sh is not None:
                    sh.force_base = True
            if self._q.empty():
                self._idle.set()

    def _retry_sleep(self, attempt: int) -> None:
        time.sleep(min(self.retry_base_s * (2 ** attempt), 0.05))

    def _guarded_write(self, eid: str, record: dict) -> bool:
        """One journal record through the ``store.write`` seam: fail/oom/
        reset retry with capped backoff; partial/poison land a torn or
        corrupt record (the CRC catches it at restore -- exactly what a
        mid-write SIGKILL leaves behind)."""
        for attempt in range(self.max_retries):
            try:
                spec = faults.check("store.write")
                rec = record
                if spec is not None and spec.kind == "partial":
                    frac = spec.arg if spec.arg is not None else 0.5
                    cut = max(0, int(len(record["blob"]) * frac))
                    rec = dict(record, blob=record["blob"][:cut])
                elif spec is not None and spec.kind == "poison":
                    b = bytearray(record["blob"])
                    b[len(b) // 2] ^= 0xFF
                    rec = dict(record, blob=bytes(b))
                self.store.write(RECORD_TYPE, eid, rec)
                return True
            except (faults.InjectedFault, ConnectionResetError, OSError):
                self.stats["write_retries"] += 1
                _RETRIES.inc()
                self._retry_sleep(attempt)
        return False

    def _guarded_manifest_put(self, sid: str, epoch: int, tick: int,
                              kind: str, crc: int, nbytes: int) -> bool:
        val = json.dumps({"epoch": epoch, "tick": tick, "kind": kind,
                          "crc": crc, "nbytes": nbytes})
        for attempt in range(self.max_retries):
            try:
                spec = faults.check("store.manifest")
                v = val
                if spec is not None and spec.kind == "partial":
                    frac = spec.arg if spec.arg is not None else 0.5
                    v = val[:max(0, int(len(val) * frac))]
                elif spec is not None and spec.kind == "poison":
                    v = "\x00" + val[1:]
                self.manifest.put(_manifest_key(sid, epoch), v)
                return True
            except (faults.InjectedFault, ConnectionResetError, OSError):
                self.stats["manifest_retries"] += 1
                _RETRIES.inc()
                self._retry_sleep(attempt)
        return False

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the writer has landed everything enqueued so far
        (tests/benches assert durable state; close() calls this)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.empty() and self._idle.is_set():
                return True
            time.sleep(0.002)
        return False

    def close(self, drain: bool = True) -> None:
        if self._writer is not None:
            if drain:
                self.drain()
            self._stop.set()
            self._writer.join(timeout=5.0)
            self._writer = None

    # -- restore (the crash-restart half) ---------------------------------

    def _guarded_read(self, eid: str) -> dict | None:
        for attempt in range(self.max_retries):
            try:
                spec = faults.check("store.read")
                rec = self.store.read(RECORD_TYPE, eid)
                if rec is not None and spec is not None:
                    if spec.kind == "partial":
                        frac = spec.arg if spec.arg is not None else 0.5
                        cut = max(0, int(len(rec["blob"]) * frac))
                        rec = dict(rec, blob=rec["blob"][:cut])
                    elif spec.kind == "poison":
                        b = bytearray(rec["blob"])
                        if b:
                            b[len(b) // 2] ^= 0xFF
                        rec = dict(rec, blob=bytes(b))
                return rec
            except (faults.InjectedFault, ConnectionResetError, OSError):
                self.stats["read_retries"] += 1
                _RETRIES.inc()
                self._retry_sleep(attempt)
        return None

    def _manifest_entries(self, space_id: str) -> list[dict]:
        lo = _manifest_key(space_id, 0)[:-8]
        hi = lo + _MANIFEST_END
        for attempt in range(self.max_retries):
            try:
                faults.check("store.manifest")
                rows = self.manifest.find(lo, hi)
                break
            except (faults.InjectedFault, ConnectionResetError, OSError):
                self.stats["manifest_retries"] += 1
                _RETRIES.inc()
                self._retry_sleep(attempt)
        else:
            return []
        out = []
        for _k, v in rows:
            try:
                e = json.loads(v)
                out.append({"epoch": int(e["epoch"]), "tick": int(e["tick"]),
                            "kind": e["kind"], "crc": int(e["crc"])})
            except (ValueError, KeyError, TypeError):
                # torn/poisoned manifest line: skip it; the chain walk
                # below treats the epoch as absent and falls back
                self.stats["torn_records"] += 1
                _TORN.inc()
        out.sort(key=lambda e: e["epoch"])
        return out

    def _load_record(self, space_id: str, ent: dict, cache: dict):
        """One CRC-verified journal payload, memoized; None when the
        record is missing, torn, or disagrees with its manifest entry."""
        import msgpack

        epoch = ent["epoch"]
        if epoch in cache:
            return cache[epoch]
        rec = self._guarded_read(_record_eid(space_id, epoch))
        payload = None
        if rec is not None:
            blob = rec.get("blob", b"")
            if _crc(blob) == rec.get("crc") == ent["crc"] \
                    and rec.get("epoch") == epoch:
                try:
                    payload = msgpack.unpackb(blob, raw=False)
                except Exception:
                    payload = None
        if payload is None:
            self.stats["torn_records"] += 1
            _TORN.inc()
        cache[epoch] = payload
        return payload

    def restore(self, space_id: str):
        """Newest fully-consistent state for ``space_id``: walk the
        manifest newest-first, validate the base+delta chain record by
        record (per-record CRC), and fold it into a migration snapshot.
        A torn tail -- the record the SIGKILL interrupted, an injected
        ``partial``/``poison`` write -- just shortens the chain: the
        result is the last consistent epoch.  Returns ``(snap, tick,
        epoch)`` or None when no consistent chain exists."""
        with _T.span("ckpt.restore"):
            entries = self._manifest_entries(space_id)
            if not entries:
                return None
            by_epoch = {e["epoch"]: e for e in entries}
            cache: dict[int, dict | None] = {}
            for ent in reversed(entries):
                chain = self._chain_for(ent, by_epoch, cache, space_id)
                if chain is None:
                    continue
                snap, tick = self._fold_chain(chain)
                self.stats["restores"] += 1
                return snap, tick, ent["epoch"]
        return None

    def _chain_for(self, ent: dict, by_epoch: dict, cache: dict,
                   space_id: str):
        """The validated base..ent payload chain, or None if any link is
        missing/torn."""
        chain = []
        e = ent["epoch"]
        while True:
            cur = by_epoch.get(e)
            if cur is None:
                return None
            payload = self._load_record(space_id, cur, cache)
            if payload is None:
                return None
            chain.append((cur, payload))
            if payload["kind"] == "base":
                break
            e -= 1
        chain.reverse()
        return chain

    @staticmethod
    def _fold_chain(chain):
        """base payload + ordered deltas -> (_build_snapshot dict, tick)."""
        ent, base = chain[0]
        cap = int(base["capacity"])
        wcols = int(base["words_cols"])
        x = np.zeros(cap, np.float32)
        z = np.zeros(cap, np.float32)
        _apply_pos_packet(base["packet"], x, z)
        r = np.frombuffer(base["r"], np.float32).copy()
        act = np.frombuffer(base["act"], bool).copy()
        words = np.frombuffer(base["words"], np.uint32) \
            .reshape(cap, wcols).copy()
        sub = bool(base["sub"])
        tick = int(base["tick"])
        interest = base.get("interest")
        for ent, d in chain[1:]:
            _apply_pos_packet(d.get("pos"), x, z)
            if "r_idx" in d:
                r[np.frombuffer(d["r_idx"], np.int64)] = \
                    np.frombuffer(d["r_val"], np.float32)
            if "act_idx" in d:
                act[np.frombuffer(d["act_idx"], np.int64)] = \
                    np.frombuffer(d["act_val"], bool)
            for pk, pb in d.get("pages", {}).items():
                p = int(pk)
                words[p * PAGE_ROWS:(p + 1) * PAGE_ROWS] = \
                    np.frombuffer(pb, np.uint32).reshape(-1, wcols)
            sub = bool(d["sub"])
            tick = int(d["tick"])
            if "interest" in d:
                interest = d["interest"]
        snap = _build_snapshot(cap, x, z, r, act, sub, words)
        if interest is not None:
            snap["interest"] = interest
        return snap, tick

    def restore_into(self, engine, space_id: str, tier: str | None = None,
                     backend: str | None = None):
        """Crash-restart entry point: restore the newest consistent state
        onto a fresh slot of ``engine`` through the evacuation/migration
        ``import_snapshot`` path, and resume tracking (next capture is a
        fresh base at the next epoch -- any torn records above the
        restored epoch are simply overwritten).  Returns ``(handle, tick,
        epoch)`` or None."""
        res = self.restore(space_id)
        if res is None:
            return None
        snap, tick, epoch = res
        if tier is not None:
            h = engine._create_handle(snap["capacity"], tier)
        else:
            h = engine.create_space(snap["capacity"], backend)
        h.bucket.import_snapshot(h.slot, snap)
        if "interest" in snap:
            # stash for attach_interest: the restoring space re-declares
            # its policies (code), the payload restores their state
            h._interest_snapshot = snap["interest"]
        sh = _SpaceShadow(h)
        sh.epoch = epoch + 1
        sh.enqueued_tick = sh.acked_tick = tick
        sh.acked_epoch = epoch
        self._shadows[space_id] = sh
        return h, tick, epoch


# -- deterministic crash-restart driver --------------------------------------
#
# ``python -m goworld_tpu.engine.checkpoint --dir D ...`` runs one seeded
# AOI walk with checkpointing armed, journaling one line per tick
# ("<tick> <crc32:08x> <n_events>", line-buffered -- the delivered-stream
# record a SIGKILL cannot retract) and, at --kill-at K, SIGKILLs ITSELF
# right after journaling tick K: deterministic, and still a real kill -9
# (no atexit, no writer drain, torn journal tails included).  With
# --resume it instead restores from the checkpoint dir and replays
# ticks R+1..N.  crash_restart_scenario() is the parent harness the
# restart bench / smoke / tests share: oracle run, crashed run, resumed
# run, then the dispatcher-bounded-replay merge (overlap ticks must agree
# bit-exactly -- the exactly-once argument -- and the union must equal
# the oracle: events_lost == 0).


def _walk_frames(cap: int, world: float, ticks: int, seed: int):
    rng = np.random.default_rng(seed)
    x = rng.uniform(0.0, world, cap).astype(np.float32)
    z = rng.uniform(0.0, world, cap).astype(np.float32)
    frames = []
    for _ in range(ticks):
        x = x + rng.uniform(-3.0, 3.0, cap).astype(np.float32)
        z = z + rng.uniform(-3.0, 3.0, cap).astype(np.float32)
        frames.append((x.copy(), z.copy()))
    return frames


def _open_backends(base_dir: str):
    from ..kvdb.backends import FilesystemKVDB
    from ..storage.backends import FilesystemEntityStorage

    return (FilesystemEntityStorage(os.path.join(base_dir, "store")),
            FilesystemKVDB(os.path.join(base_dir, "kvdb")))


def _tick_crc(e, lv) -> tuple[int, int]:
    e = np.ascontiguousarray(e, np.int32)
    lv = np.ascontiguousarray(lv, np.int32)
    return (zlib.crc32(lv.tobytes(), zlib.crc32(e.tobytes(), 0)),
            len(e) + len(lv))


def _driver(argv=None) -> int:
    import argparse
    import signal
    import sys

    from .aoi import AOIEngine

    ap = argparse.ArgumentParser(
        description="deterministic checkpoint crash-restart driver")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--journal", required=True)
    ap.add_argument("--ticks", type=int, default=32)
    ap.add_argument("--cap", type=int, default=256)
    ap.add_argument("--world", type=float, default=400.0)
    ap.add_argument("--tier", default="tpu",
                    choices=("cpu", "cpp", "tpu"))
    ap.add_argument("--mode", default="continuous",
                    choices=("interval", "continuous"))
    ap.add_argument("--interval", type=int, default=4)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--kill-at", type=int, default=-1)
    ap.add_argument("--no-checkpoint", action="store_true")
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args(argv)

    frames = _walk_frames(args.cap, args.world, args.ticks, args.seed)
    r = np.full(args.cap, 100.0, np.float32)
    act = np.ones(args.cap, bool)
    eng = AOIEngine("cpu")
    ctl = None
    if not args.no_checkpoint:
        store, kv = _open_backends(args.dir)
        ctl = CheckpointController(eng, store, kv, mode=args.mode,
                                   interval=args.interval)
    start = 0
    jf = open(args.journal, "a", buffering=1)
    if args.resume:
        res = ctl.restore_into(eng, "bench", tier=args.tier)
        if res is None:
            print("no consistent checkpoint", file=sys.stderr)
            return 2
        h, tick, epoch = res
        start = tick
        jf.write(f"# restored epoch={epoch} tick={tick}\n")
    else:
        h = eng._create_handle(args.cap, args.tier)
        if ctl is not None:
            ctl.track("bench", h)
    for t in range(start + 1, args.ticks + 1):
        x, z = frames[t - 1]
        t0 = time.perf_counter()
        eng.submit(h, x, z, r, act)
        eng.flush()
        e, lv = eng.take_events(h)
        wall = time.perf_counter() - t0
        crc, n = _tick_crc(e, lv)
        jf.write(f"{t} {crc:08x} {n} {wall:.6f}\n")
        if ctl is not None:
            ctl.step(t)
        if t == args.kill_at:
            os.kill(os.getpid(), signal.SIGKILL)
    if ctl is not None:
        ctl.drain()
        ctl.close()
    return 0


def _read_journal(path: str) -> tuple[dict, dict, int]:
    """{tick: crc_hex}, {tick: n_events}, restored_tick (-1 if none)."""
    crcs, counts, restored = {}, {}, -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                if "restored" in line:
                    try:
                        restored = int(
                            line.rsplit("tick=", 1)[1].split()[0])
                    except (IndexError, ValueError):
                        pass  # torn marker: treat as no restore record
                continue
            # torn-tolerant: a kill -9 mid-append can leave a truncated
            # final line; it carries no complete (tick, crc, count) fact,
            # so it is dropped, exactly like a torn checkpoint record
            parts = line.split()
            try:
                t = int(parts[0])
                crc, n = parts[1], int(parts[2])
            except (IndexError, ValueError):
                continue
            crcs[t] = crc
            counts[t] = n
    return crcs, counts, restored


def crash_restart_scenario(base_dir: str, cap: int = 256,
                           world: float = 400.0, ticks: int = 32,
                           kill_at: int = 20, tier: str = "tpu",
                           mode: str = "continuous", interval: int = 4,
                           seed: int = 17) -> dict:
    """Parent harness: oracle run, SIGKILLed run, resumed run, then the
    bounded-replay merge.  Returns the parity verdict + recovery stats
    (the engine_restart bench record's core fields)."""
    import subprocess
    import sys

    os.makedirs(base_dir, exist_ok=True)
    ck_dir = os.path.join(base_dir, "ckpt")
    oracle_j = os.path.join(base_dir, "oracle.journal")
    crash_j = os.path.join(base_dir, "crash.journal")
    resume_j = os.path.join(base_dir, "resume.journal")
    for p in (oracle_j, crash_j, resume_j):
        if os.path.exists(p):
            os.unlink(p)
    common = [sys.executable, "-m", "goworld_tpu.engine.checkpoint",
              "--dir", ck_dir, "--ticks", str(ticks), "--cap", str(cap),
              "--world", str(world), "--tier", tier, "--mode", mode,
              "--interval", str(interval), "--seed", str(seed)]
    env = dict(os.environ)
    rc_oracle = subprocess.run(
        common + ["--journal", oracle_j, "--no-checkpoint"],
        env=env).returncode
    crashed = subprocess.run(
        common + ["--journal", crash_j, "--kill-at", str(kill_at)], env=env)
    t0 = time.perf_counter()
    rc_resume = subprocess.run(
        common + ["--journal", resume_j, "--resume"], env=env).returncode
    restart_wall_s = time.perf_counter() - t0
    o_crc, o_n, _ = _read_journal(oracle_j)
    c_crc, c_n, _ = _read_journal(crash_j)
    r_crc, r_n, restored_tick = _read_journal(resume_j)
    # bounded replay: ticks both sides delivered must agree bit-exactly
    # (the dedup the dispatcher's exactly-once replay performs); the
    # merged stream takes each tick once
    overlap = sorted(set(c_crc) & set(r_crc))
    replay_ok = all(c_crc[t] == r_crc[t] for t in overlap)
    merged = dict(c_crc)
    merged.update(r_crc)
    merged_n = dict(c_n)
    merged_n.update(r_n)
    parity_ok = (replay_ok and set(merged) == set(o_crc)
                 and all(merged[t] == o_crc[t] for t in o_crc))
    events_lost = sum(o_n.values()) - sum(
        merged_n.get(t, 0) for t in o_n)
    return {
        "ticks": ticks,
        "kill_tick": kill_at,
        "restored_tick": restored_tick,
        "ticks_to_recover": kill_at - restored_tick,
        "replayed_overlap_ticks": len(overlap),
        "replay_parity_ok": replay_ok,
        "parity_ok": bool(parity_ok),
        "events_lost": int(events_lost),
        "restart_wall_s": restart_wall_s,
        "oracle_events": int(sum(o_n.values())),
        "crash_rc": crashed.returncode,
        "oracle_rc": rc_oracle,
        "resume_rc": rc_resume,
    }


if __name__ == "__main__":
    raise SystemExit(_driver())
