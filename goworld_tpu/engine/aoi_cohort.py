"""The cohort bucket tier: many spaces, one device program per tick.

A ``_TPUBucket``'s packed state already carries a leading slot axis
(``[S, C, W]``) and its dispatch already ticks every staged slot in one
fused launch -- so the slot axis IS the space-stacking axis (ROADMAP
#2, ops/aoi_cohort.py).  What the cohort tier adds on top of the plain
bucket is the membership contract:

* spaces of *different* (small) capacities share the bucket: the engine
  rounds each up to the bucket's pow2 ladder shape (ops/aoi_cohort
  ``cohort_shape``) and the padded tail stays inactive, which the
  predicate ignores bit-exactly;
* the bucket is the blast radius of the ``aoi.cohort`` fault seam,
  probed at dispatch BEFORE any staging mutates device or shadow state
  -- any fired kind flags the bucket for demotion and the engine
  rebuilds every member space onto its own solo bucket the same flush,
  re-staging this tick's inputs so the republish is same-tick and
  bit-exact (``AOIEngine._demote_cohort``);
* the paged free list (inherited) is bucket-wide, so a quiet member
  space lends page capacity to a crowded one by construction.

Everything else -- delta staging, fused dispatch, recovery ladder,
export/import/evacuate -- is inherited unchanged from ``_TPUBucket``;
the chip-loss failover hooks the fault-seam-coverage rule demands come
with the inheritance.
"""

from __future__ import annotations

from .. import faults
from .aoi import _TPUBucket, _device_fault


class _CohortTPUBucket(_TPUBucket):
    """Shared ladder-shaped device bucket stacking many small spaces."""

    def __init__(self, capacity: int, **kw):
        super().__init__(capacity, **kw)
        self.cohort = True
        # set by dispatch when the aoi.cohort seam fires; consumed by
        # AOIEngine (flush demotes the bucket before its harvest slot)
        self._cohort_demote = False
        self.stats["cohort_dispatches"] = 0
        self.stats["cohort_demotions"] = 0

    def dispatch(self) -> None:
        """Probe the ``aoi.cohort`` seam, then run the inherited
        dispatch.  The probe comes FIRST -- like ``aoi.device`` in
        ``_dispatch_device`` -- so a firing seam leaves ``_staged`` and
        the host shadows untouched: the engine can re-stage this tick's
        inputs onto the demotion targets and republish same-tick."""
        if not self._cohort_demote:
            try:
                spec = faults.check("aoi.cohort")
            except Exception as e:
                if not (_device_fault(e)
                        or isinstance(e, ConnectionResetError)):
                    raise
                spec = e
            if spec is not None:
                # ANY fired kind demotes (the aoi.ingest/aoi.interest
                # discipline): a cohort whose shared program is suspect
                # must not tick ANY member on it
                self._cohort_demote = True
                self.stats["cohort_demotions"] += 1
        if self._cohort_demote:
            # park nothing: the engine tears this bucket down before
            # harvest; an inflight (pipelined) tick is drained by the
            # per-slot snapshot export during demotion
            return
        if self._staged:
            self.stats["cohort_dispatches"] += 1
        super().dispatch()
