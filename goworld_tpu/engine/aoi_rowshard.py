"""Observer-row-sharded AOI for ONE oversized space (the zipf100k answer).

The mesh bucket (engine/aoi_mesh) shards SPACES over chips -- a single space
is chip-local by design, so a space too hot for one chip's real-time budget
(BASELINE's zipf100k: 100k entities, ONE space, 161-165 ms device tick vs the
100 ms cadence in round 4) had no scaling story.  This bucket shards WITHIN
the space: chip d owns interest rows [d*C/n, (d+1)*C/n) -- its block of
observers -- evaluated against ALL C candidates.  Work and interest-state
memory split n_dev ways; candidates (x, z, active) are replicated at H2D
(~1 MB/tick at C=131072), and every chip's diff extraction is chip-local, so
the tick uses ZERO inter-chip collectives, exactly like the slot-sharded
bucket.

The reference's answer to an oversized space is capacity-capping and
splitting (/root/reference/examples/unity_demo/SpaceService.go:91-109) plus a
pluggable-AOI seam meant to scale (/root/reference/engine/entity/Space.go:106;
see ROADMAP.md for the scaling north-star); this supersedes both: one logical
space, n chips, bit-exact events.

Design notes:
  * One bucket instance per space (``exclusive``): the engine keys it
    uniquely and drops it at release -- at C=131072 the packed state is
    2 GB mesh-wide; slot reuse machinery would just pin it.
  * The kernel runs in RECTANGULAR mode (ops/aoi_pallas ``cols=``/
    ``row_ids=``): each chip's [C/n] observer block against the replicated
    [C] candidate arrays, prev block [C/n, W].  Global observer ids ride
    ``row_ids`` so self-exclusion holds across blocks.
  * Events: per-chip chunk extraction + wire encode, identical machinery to
    the mesh bucket; a chip's global flat word index is just offset by
    d * (C/n) * W, and expansion runs with n_spaces=1.
  * Flush is synchronous (dispatch + harvest in one call): events arrive
    same-tick like the CPU oracle.  ``pipeline`` is accepted for engine
    symmetry; stream D2H still overlaps via async copies inside the flush.
  * No host mirror: at this size a [C, W] mirror is the whole interest
    state.  ``derive_row``/``derive_col`` fetch one observer's row [W]
    (16 KB) or one column's word across rows [C] on demand --
    Space.derive_interests/derive_observers prefer them when present.
  * Subscription (set_subscribed False) masks the whole space's change
    stream on device: an all-plain 100k NPC space pays kernel time only,
    no fetch, no decode.
"""

from __future__ import annotations

import time

import numpy as np

from .. import faults
from ..telemetry import trace as _T
from ..ops import aoi_predicate as P
from ..ops import dispatch_count as DC
from ..ops import events as EV
from ..ops import aoi_emit as AE
from .aoi import (_Bucket, _CapDecay, _build_snapshot, _device_fault,
                  _emit_expand, _kernelish_fault, _packed_predicate,
                  _paged_absorb_chip, _unpack_positions)
from ..parallel.compat import shard_map

_LANES = 128


class _RowShardTPUBucket(_Bucket):
    """ONE space, interest rows sharded over the mesh's 'space' axis."""

    exclusive = True  # engine: one bucket per space, dropped at release

    def __init__(self, capacity: int, mesh, pipeline: bool = False,
                 delta_staging: bool = True, emit: str = "vector",
                 paged: bool = False, cross_tick: bool = False,
                 fused: bool = False):
        super().__init__(capacity)
        # fused steady tick (ops/aoi_fused contract, per chip): both
        # packet scatters (sharded block + replicated candidates) fold
        # INTO the rectangular step, so a steady tick is ONE program
        # launch (vs scatter + step); see _dispatch_fused
        self.fused = bool(fused)
        import jax  # noqa: F401  (fail fast if jax is unavailable)

        # paged overflow absorber (docs/perf.md, paged storage): a chip
        # whose encoded stream overflows its caps is recovered through
        # the device-side page allocator (used pages + spilled bins D2H)
        # instead of growing the caps (a recompile) and fetching its full
        # diff grid; counted in page_spills, never decode_overflow
        self.paged = bool(paged)
        self._n_pages = 0
        self._page_free = None
        self._pages = None  # _PageDecay, lazily sized at first absorb

        # emit path for the harvested word streams (docs/perf.md emit
        # paths; see _MeshTPUBucket -- "vector" and "host" coincide here)
        self._emit = emit
        self._emit_requested = emit

        self.mesh = mesh
        self.n_dev = mesh.n_devices
        if capacity % (self.n_dev * 128):
            raise ValueError(
                f"row-sharded capacity {capacity} must be a multiple of "
                f"n_dev*128 = {self.n_dev * 128}")
        self.c_local = capacity // self.n_dev
        self.pipeline = pipeline  # accepted for symmetry; flush is sync
        self.cross_tick = bool(cross_tick)  # likewise: never deferred here
        self.prev = None  # [C, W] uint32, rows sharded over the mesh
        # persistent staged inputs [C]; unstaged flushes step nothing
        self._hx = np.zeros(capacity, np.float32)
        self._hz = np.zeros(capacity, np.float32)
        self._hr = np.zeros(capacity, np.float32)
        self._hact = np.zeros(capacity, bool)
        self._pending_clear: list[int] = []
        self._subscribed = True
        # per-chip extraction caps (static shapes, grow on overflow, decay
        # via the shared window)
        self._max_chunks = 4096
        self._kcap = 8
        self._max_gaps = 2048
        self._max_exc = 16384
        self._caps = _CapDecay(nd_floor=4096)
        self._step_cache: dict[tuple, object] = {}
        self._maint_cache: dict[tuple, object] = {}
        self._scratch: dict[tuple, tuple] = {}
        self._h2d_cache: dict[str, tuple] = {}
        # delta staging: persistent device copies of x/z -- one SHARDED
        # block pair (observer rows) and one REPLICATED candidate pair --
        # bitwise-identical to the _hx/_hz shadows.  Steady flushes ship
        # one replicated (cols, x, z) packet; each chip scatters its own
        # column block plus its replicated copy (no collectives).
        self.delta_staging = delta_staging
        self._dxs = self._dzs = None  # sharded [C]
        self._dxr = self._dzr = None  # replicated [C]
        self._xz_stale = True
        self._delta_max_frac = 0.25
        # fault tolerance (docs/robustness.md): NO standing mirror at this
        # size -- the durable copies are the input shadows (prev equals
        # their predicate except between set_prev and the next step, which
        # _seed_prev covers under an active plan) plus _host_prev, the
        # recovered state carried host-side while the device is down
        self._ft = faults.active()
        # chip-loss failover: True after a DeviceLost recovery -- the
        # engine rebuilds the space onto a fresh bucket at the end of the
        # current flush (docs/robustness.md)
        self._evacuating = False
        self._calc_level = 0  # 0 = platform default, 1 = dense, 2 = oracle
        self._fault_phase = "stage"
        self._seed_prev: np.ndarray | None = None
        self._host_prev: np.ndarray | None = None
        self._cur_old: tuple | None = None
        self._tick_inflight = False  # restage done, events not yet harvested
        # split-phase flush (docs/perf.md): dispatch() parks what harvest()
        # must do (see _TPUBucket._sched for the grammar); this bucket is
        # not pipelined, so the parked record is always the CURRENT tick's
        self._sched: tuple | None = None
        self.stats = {"h2d_bytes": 0, "delta_flushes": 0, "full_flushes": 0,
                      "rebuilds": 0, "fallbacks": 0, "host_ticks": 0,
                      "poisoned": 0, "calc_level": 0, "decode_overflow": 0,
                      "page_spills": 0, "page_occupancy": 0.0,
                      "fused_dispatches": 0, "fused_demotions": 0,
                      "emit_path": AE.EMIT_LEVEL[emit]}
        self._pred = (512, 64, 256)
        self.full_roundtrips = 0
        self.perf = {"stage_s": 0.0, "fetch_s": 0.0, "decode_s": 0.0,
                     "emit_s": 0.0}

    @property
    def _steady(self) -> bool:
        return self._caps.steady

    # -- slot management (exactly one) --------------------------------------
    def acquire_slot(self) -> int:
        if self.n_slots:
            raise RuntimeError("row-sharded bucket holds exactly one space")
        return super().acquire_slot()

    def _grow_to(self, n_slots: int) -> None:
        pass  # single slot; device state allocates lazily at first flush

    def _reset_slot(self, slot: int) -> None:
        pass  # fresh bucket per space: nothing to reset

    def set_subscribed(self, slot: int, flag: bool) -> None:
        if self._subscribed != bool(flag):
            self._xz_stale = True  # sub change: full-restage fallback
        self._subscribed = bool(flag)

    # -- device programs ----------------------------------------------------
    def _replicated(self, arr):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PS

        return jax.device_put(arr, NamedSharding(self.mesh.mesh, PS()))

    def _h2d(self, role: str, arr: np.ndarray, replicated: bool = False):
        cached = self._h2d_cache.get(role)
        if cached is not None and cached[0].shape == arr.shape and \
                np.array_equal(cached[0], arr):
            return cached[1]
        faults.check("aoi.h2d")
        dev = self._replicated(arr) if replicated else self.mesh.device_put(arr)
        self._h2d_cache[role] = (arr.copy(), dev)
        self.stats["h2d_bytes"] += arr.nbytes
        return dev

    def _delta_fn(self, npk: int):
        """Jitted donated per-shard scatter of one replicated (cols, x, z)
        packet into BOTH device x/z copies: the sharded observer blocks
        (column indices localized per chip, out-of-block entries dropped)
        and the replicated candidate copies (every chip applies the whole
        packet) -- no cross-chip collectives either way."""
        key = ("delta", npk)
        fn = self._maint_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as PS

            from ..ops.aoi_stage import delta_scatter_1d
            from ..parallel.compat import shard_map

            cl = self.c_local
            axis = self.mesh.axis

            def _local(xs, zs, xr, zr, cols, xv, zv):
                lo = jax.lax.axis_index(axis) * cl
                xs, zs = delta_scatter_1d(xs, zs, cols, xv, zv,
                                          col_lo=lo, n_cols=cl)
                xr, zr = delta_scatter_1d(xr, zr, cols, xv, zv)
                return xs, zs, xr, zr

            spec, rep = PS(axis), PS()
            local = shard_map(_local, mesh=self.mesh.mesh,
                              in_specs=(spec, spec, rep, rep, rep, rep, rep),
                              out_specs=(spec, spec, rep, rep),
                              check_vma=False)
            self._maint_cache[key] = fn = jax.jit(
                local, donate_argnums=(0, 1, 2, 3))
        return fn

    def _stage_xz(self, old_x, old_z, old_r, old_act) -> None:
        """Bring the device-resident x/z copies (sharded + replicated) up
        to date with the host shadow: sparse packet on the steady path,
        full re-upload on the fallbacks (clear_entity, r/act/sub change,
        changed fraction above _delta_max_frac, or delta staging
        disabled).  Bit-pattern diff: see _TPUBucket._stage_inputs."""
        from ..ops import aoi_stage as AS

        diff = (self._hx.view(np.uint32) != old_x.view(np.uint32)) \
            | (self._hz.view(np.uint32) != old_z.view(np.uint32))
        n_changed = np.count_nonzero(diff)  # host numpy scalar
        if not (np.array_equal(self._hr, old_r)
                and np.array_equal(self._hact, old_act)):
            self._xz_stale = True  # r/act change: full-restage fallback
        if (self.delta_staging and not self._xz_stale
                and self._dxs is not None
                and n_changed <= self._delta_max_frac * diff.size):
            if n_changed:
                faults.check("aoi.delta")
                cols = np.nonzero(diff)[0]
                _, cols, xv, zv = AS.pad_packet(cols, cols, self._hx[cols],
                                                self._hz[cols],
                                                page_granular=self.paged)
                DC.record()
                self._dxs, self._dzs, self._dxr, self._dzr = \
                    self._delta_fn(len(cols))(
                        self._dxs, self._dzs, self._dxr, self._dzr,
                        cols, xv, zv)
                self.stats["h2d_bytes"] += \
                    cols.nbytes + xv.nbytes + zv.nbytes
            self.stats["delta_flushes"] += 1
            return
        faults.check("aoi.h2d")
        put = self.mesh.device_put
        self._dxs, self._dzs = put(self._hx), put(self._hz)
        self._dxr = self._replicated(self._hx)
        self._dzr = self._replicated(self._hz)
        self.stats["h2d_bytes"] += 2 * (self._hx.nbytes + self._hz.nbytes)
        self._xz_stale = False
        self.stats["full_flushes"] += 1

    def _ensure_prev(self):
        if self.prev is None:
            faults.check("aoi.grow")  # the lazy state allocation seam
            src = (self._host_prev if self._host_prev is not None
                   else np.zeros((self.capacity, self.W), np.uint32))
            self.prev = self.mesh.device_put(np.ascontiguousarray(src))
            if self._host_prev is not None:  # rebuild after device loss
                self.stats["h2d_bytes"] += src.nbytes
                self._host_prev = None

    def _sharded_step(self, npk: int | None = None):
        """Jitted shard_map rectangular step for the current static caps.

        ``npk`` (fused mode, ops/aoi_fused contract): fold the delta
        scatter of one replicated (cols, xv, zv) packet of that padded
        length into the program -- each chip scatters its own column
        block plus its replicated candidate copy, then steps from the
        freshly scattered x/z -- so a steady tick is ONE launch instead
        of scatter + step.  The four device x/z copies ride as donated
        inputs and come back as extra outputs."""
        key = (self._max_chunks, self._kcap, self._max_gaps, self._max_exc,
               self._calc_level, npk)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        if len(self._step_cache) > 4:
            self._step_cache.clear()
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PS

        from ..ops.aoi_dense import aoi_step_chg
        from ..ops.aoi_stage import delta_scatter_1d

        # calculator fallback chain level 1: force the fused dense path
        platform = "cpu" if self._calc_level >= 1 else self.mesh.platform
        mc, kcap = self._max_chunks, self._kcap
        mg, mx = self._max_gaps, self._max_exc
        cl = self.c_local
        axis = self.mesh.axis
        fused = npk is not None

        def _body(prev_blk, chg_buf, vals_buf, nv_buf, lane_buf, csel_buf,
                  xs, zs, rs, acts, x_all, z_all, act_all, sub):
            lo = jax.lax.axis_index(axis) * cl
            rid = (lo + jnp.arange(cl, dtype=jnp.int32))[None]
            # platform routing lives in ops/aoi_dense.aoi_step_chg
            new, chg = aoi_step_chg(
                xs[None], zs[None], rs[None], acts[None], prev_blk[None],
                cols=(x_all[None], z_all[None], act_all[None]),
                row_ids=rid, platform=platform)
            new, chg = new[0], chg[0]
            # subscription mask (see engine/aoi._fused_bucket_step): ``new``
            # stays unmasked -- prev is authoritative
            chg = jnp.where(sub, chg, jnp.uint32(0))
            vals, nv, lane, csel, ccnt, nd, mcc = EV.extract_chunks(
                chg, mc, kcap, aux=new, lanes=_LANES)
            (rowb, bitpos, woff, base_row, n_esc, esc_rows, exc_gidx,
             exc_chg, exc_new, exc_n) = EV.encode_row_stream(
                vals, nv, lane, csel, ccnt, w=_LANES, max_gaps=mg,
                max_exc=mx)
            scalars = jnp.stack([nd, mcc, base_row, n_esc, exc_n])
            chg_buf = chg_buf.at[:].set(chg)
            vals_buf = vals_buf.at[:].set(vals)
            nv_buf = nv_buf.at[:].set(nv)
            lane_buf = lane_buf.at[:].set(lane)
            csel_buf = csel_buf.at[:].set(csel)
            return (new, chg_buf, vals_buf, nv_buf, lane_buf, csel_buf,
                    rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
                    exc_new, scalars[None])

        spec = PS(self.mesh.axis)
        rep = PS()
        if fused:
            def _local(prev_blk, chg_buf, vals_buf, nv_buf, lane_buf,
                       csel_buf, xs, zs, rs, acts, xr, zr, act_all, sub,
                       cols, xv, zv):
                lo = jax.lax.axis_index(axis) * cl
                xs, zs = delta_scatter_1d(xs, zs, cols, xv, zv,
                                          col_lo=lo, n_cols=cl)
                xr, zr = delta_scatter_1d(xr, zr, cols, xv, zv)
                out = _body(prev_blk, chg_buf, vals_buf, nv_buf, lane_buf,
                            csel_buf, xs, zs, rs, acts, xr, zr, act_all,
                            sub)
                return out + (xs, zs, xr, zr)

            local = shard_map(
                _local,
                mesh=self.mesh.mesh,
                in_specs=(spec,) * 10 + (rep,) * 7,
                out_specs=(spec,) * 16 + (rep, rep),
                check_vma=False,
            )
            fn = jax.jit(local,
                         donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 10, 11))
        else:
            local = shard_map(
                _body,
                mesh=self.mesh.mesh,
                in_specs=(spec,) * 10 + (rep, rep, rep, rep),
                out_specs=(spec,) * 14,
                check_vma=False,
            )
            fn = jax.jit(local, donate_argnums=(0, 1, 2, 3, 4, 5))
        self._step_cache[key] = fn
        return fn

    def _maintenance_fn(self):
        key = True
        fn = self._maint_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PS

        cl = self.c_local
        axis = self.mesh.axis
        W = self.W

        def _local(prev_blk, rows, col_w, col_m):
            # row clears: global row -> local.  Out-of-block rows must map
            # to an index >= cl (mode="drop"); a bare ``rows - lo`` would
            # go NEGATIVE for earlier chips' rows and .at[] wraps negative
            # indices numpy-style BEFORE the mode applies -- clearing the
            # wrong row on every other chip.
            lo = jax.lax.axis_index(axis) * cl
            in_blk = (rows >= lo) & (rows < lo + cl)
            lr = jnp.where(in_blk, rows - lo, cl)
            prev_blk = prev_blk.at[lr].set(jnp.uint32(0), mode="drop")
            # column clears: AND the mask into word col_w of EVERY row
            # (col_w == W pads are dropped)
            cur = prev_blk.at[:, col_w].get(mode="fill", fill_value=0)
            prev_blk = prev_blk.at[:, col_w].set(cur & col_m, mode="drop")
            return prev_blk

        spec = PS(self.mesh.axis)
        rep = PS()
        local = shard_map(
            _local, mesh=self.mesh.mesh,
            in_specs=(spec, rep, rep, rep), out_specs=spec,
            check_vma=False)
        fn = jax.jit(local, donate_argnums=(0,))
        self._maint_cache[key] = fn
        return fn

    # -- maintenance --------------------------------------------------------
    def clear_entity(self, slot: int, entity_slot: int) -> None:
        self._pending_clear.append(entity_slot)
        # keep the cached inputs consistent (departed entity inactive) so an
        # unstaged re-step cannot re-derive the cleared pairs
        self._hx[entity_slot] = 0.0
        self._hz[entity_slot] = 0.0
        self._hr[entity_slot] = 0.0
        self._hact[entity_slot] = False
        self._xz_stale = True  # device x/z diverged from the shadow
        self._h2d_cache.pop("act", None)
        self._h2d_cache.pop("r", None)

    def _apply_maintenance(self) -> None:
        if not self._pending_clear or self.prev is None:
            if self._pending_clear and self._host_prev is not None:
                # device down after a recovery: the maintenance scatter
                # lands on the host copy _ensure_prev will re-upload
                for ent in set(self._pending_clear):
                    self._host_prev[ent] = 0
                    w, b = P.word_bit_for_column(ent, self.capacity)
                    self._host_prev[:, w] &= np.uint32(
                        ~(np.uint32(1) << np.uint32(b)) & 0xFFFFFFFF)
            self._pending_clear.clear()
            return
        import jax.numpy as jnp

        ents = sorted(set(self._pending_clear))
        self._pending_clear.clear()
        col_mask: dict[int, int] = {}
        for e in ents:
            w, b = P.word_bit_for_column(e, self.capacity)
            col_mask[w] = col_mask.get(w, 0xFFFFFFFF) & (~(1 << b)
                                                         & 0xFFFFFFFF)
        cols = sorted(col_mask.items())

        def pad(seq, fill):
            if not seq:
                seq = [fill]
            n = 1
            while n < len(seq):
                n *= 2
            return seq + [fill] * (n - len(seq))

        rows = pad(ents, self.capacity)        # OOB row -> dropped
        cols = pad(cols, (self.W, 0xFFFFFFFF))  # OOB word -> dropped
        DC.record()
        self.prev = self._maintenance_fn()(
            self.prev,
            jnp.asarray(rows, jnp.int32),
            jnp.asarray([w for w, _ in cols], jnp.int32),
            jnp.asarray([m for _, m in cols], jnp.uint32),
        )

    # -- the flush ----------------------------------------------------------
    def _get_scratch(self):
        key = (self._max_chunks, self._kcap)
        sc = self._scratch.pop(key, None)
        if sc is not None:
            return key, sc
        while len(self._scratch) >= 2:
            self._scratch.pop(next(iter(self._scratch)))
        put = self.mesh.device_put
        mc, kcap = self._max_chunks, self._kcap
        n = self.n_dev * mc
        sc = (
            put(np.zeros((self.capacity, self.W), np.uint32)),
            put(np.zeros((n, kcap), np.uint32)),
            put(np.zeros((n, kcap), np.uint32)),
            put(np.full((n, kcap), -1, np.int32)),
            put(np.zeros(n, np.int32)),
        )
        return key, sc

    def flush(self) -> None:
        """Monolithic flush = dispatch immediately followed by harvest (the
        forced-sequential baseline; see _TPUBucket.flush).  Events always
        arrive same-tick -- this bucket is never pipelined across ticks."""
        self.dispatch()
        self.harvest()

    def dispatch(self) -> None:
        """Phase 1 of the split flush: maintenance + restage + H2D enqueue
        + rectangular-kernel enqueue, never blocking on device values
        (gwlint flush-phase rule); parks the harvest work in ``_sched``."""
        if self._sched is not None:
            self.harvest()  # gwlint: allow[flush-phase] -- re-entrant flush drains the prior dispatch first
        if self._calc_level >= 2:
            # calculator fallback chain bottom: host-oracle mode; the host
            # compute defers to harvest so it overlaps other buckets
            self._dispatch_oracle()
            return
        try:
            self._dispatch_device()
        except Exception as e:
            if not _device_fault(e):
                raise
            self._recover(e)
            if isinstance(e, faults.DeviceLost):
                self._mark_evacuating()

    def harvest(self) -> None:
        """Phase 2 of the split flush: the blocking per-chip fetch + decode
        of what :meth:`dispatch` enqueued.  ``_tick_inflight`` (and a live
        set_prev seed) stay armed until the events actually land, so a
        fault surfacing at the fetch recovers bit-exactly from the pre-tick
        durable state (_cur_old / _seed_prev)."""
        sched, self._sched = self._sched, None
        if sched is None:
            return
        if sched[0] == "oracle":
            self._host_tick(sched[1])
            return
        self._fault_phase = "harvest"
        try:
            self._harvest(sched[1])
        except Exception as e:
            if not _device_fault(e):
                raise
            self._recover(e)
            return
        # the tick delivered: prev == predicate(shadows) again, so a
        # set_prev seed is no longer the recovery base
        self._seed_prev = None
        self._tick_inflight = False

    def _restage_shadows(self) -> None:
        """Pop the staged tick into the persistent shadows, keeping the
        pre-tick values in _cur_old (the _stage_xz diff base, and the
        durable old state for fault recovery)."""
        (sx, sz, sr, sa) = self._staged.pop(0)
        n = len(sx)
        self._cur_old = (self._hx.copy(), self._hz.copy(),
                         self._hr.copy(), self._hact.copy())
        self._hx[:n] = sx
        self._hz[:n] = sz
        self._hr[:n] = sr
        self._hact[:] = False
        self._hact[:n] = sa
        self._staged.clear()

    def _dispatch_device(self) -> None:
        self._fault_phase = "stage"
        # device health probe: kind ``reset`` = the chip is LOST
        # (faults.DeviceLost; dispatch()'s handler marks the bucket
        # evacuating after the standard host-side recovery)
        faults.check("aoi.device")
        self._apply_maintenance()
        if not self._staged:
            return
        t0 = time.perf_counter()
        _ts = _T.t()
        self._restage_shadows()
        self._tick_inflight = True  # a restaged tick awaits its events
        old_x, old_z, old_r, old_act = self._cur_old
        self._ensure_prev()
        key, scratch = self._get_scratch()
        if self.fused and self._dispatch_fused(key, scratch, old_x, old_z,
                                               old_r, old_act, t0, _ts):
            return
        self._stage_xz(old_x, old_z, old_r, old_act)
        # np.array (not asarray): a host python bool, no device sync here
        sub = self._h2d("sub", np.array(self._subscribed), replicated=True)
        _T.lap("aoi.stage", _ts)
        _tk = _T.t()
        self._fault_phase = "kernel"
        faults.check("aoi.kernel")
        DC.record()
        out = self._sharded_step()(
            self.prev, *scratch,
            self._dxs, self._dzs,
            self._h2d("r", self._hr), self._h2d("act", self._hact),
            self._dxr, self._dzr,
            self._h2d("act_all", self._hact, replicated=True),
            sub)
        (new, chg, g_vals, g_nv, g_lane, g_csel, rowb, bitpos, woff,
         esc_rows, exc_gidx, exc_chg, exc_new, scalars) = out
        _T.lap("aoi.kernel", _tk)
        self.prev = new
        scalars.copy_to_host_async()
        # optimistic async prefetch of the streams at recent sizes -- the
        # copies ride the wire while jax finishes the dispatch; exact slices
        # refetch on a misfit
        pf = None
        if self._subscribed:
            mc = self._max_chunks
            ndp = min(mc, self._pred[0])
            escp = min(self._max_gaps, self._pred[1])
            excp = min(self._max_exc, self._pred[2])
            slices = []
            for d in range(self.n_dev):
                sl = (rowb[d * mc:d * mc + ndp],
                      bitpos[d * mc:d * mc + ndp],
                      woff[d * mc:d * mc + ndp],
                      esc_rows[d * self._max_gaps:
                               d * self._max_gaps + escp],
                      exc_gidx[d * self._max_exc:d * self._max_exc + excp],
                      exc_chg[d * self._max_exc:d * self._max_exc + excp],
                      exc_new[d * self._max_exc:d * self._max_exc + excp])
                for a in sl:
                    a.copy_to_host_async()
                slices.append(sl)
            pf = (ndp, escp, excp, slices)
        self.perf["stage_s"] += time.perf_counter() - t0
        # everything above is enqueue-only; the blocking fetch + decode
        # happen in harvest() (split-phase flush) -- _tick_inflight and any
        # set_prev seed stay armed until the events actually land
        self._sched = ("rec", {
            "caps": (self._max_chunks, self._kcap, self._max_gaps,
                     self._max_exc),
            "key": key,
            "scratch": (chg, g_vals, g_nv, g_lane, g_csel),
            "streams": (rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
                        exc_new),
            "scalars": scalars, "prefetch": pf})

    def _dispatch_fused(self, key, scratch, old_x, old_z, old_r, old_act,
                        t0, _ts) -> bool:
        """One-launch steady tick (ops/aoi_fused contract, per chip): the
        packet scatter of all four device x/z copies folds into the
        rectangular step program, so a steady tick is one enqueue per
        chip instead of scatter + step.  Returns False -- silently on an
        ineligible tick (full restage pending, r/act change, oversized
        delta), counted in ``fused_demotions`` on a seam demotion -- and
        _dispatch_device continues down the unfused path in the same
        call, bit-exact."""
        if (not self.delta_staging or self._xz_stale
                or self._dxs is None):
            return False
        if not (np.array_equal(self._hr, old_r)
                and np.array_equal(self._hact, old_act)):
            return False  # r/act change: unfused full-restage fallback
        diff = (self._hx.view(np.uint32) != old_x.view(np.uint32)) \
            | (self._hz.view(np.uint32) != old_z.view(np.uint32))
        n_changed = np.count_nonzero(diff)  # host numpy scalar
        if n_changed > self._delta_max_frac * diff.size:
            return False
        # the unfused path's staging + kernel seams, checked up front --
        # BEFORE any device mutation -- so a seam firing mid-"program"
        # demotes cleanly: the unfused retry re-runs from the exact same
        # pre-tick device state
        try:
            if n_changed:
                faults.check("aoi.delta")
            self._fault_phase = "kernel"
            faults.check("aoi.kernel")
        except Exception as e:
            if not _device_fault(e):
                raise
            self.stats["fused_demotions"] += 1
            self._fault_phase = "stage"
            return False
        from ..ops import aoi_stage as AS

        if n_changed:
            cols = np.nonzero(diff)[0]
            _, cols, xv, zv = AS.pad_packet(cols, cols, self._hx[cols],
                                            self._hz[cols],
                                            page_granular=self.paged)
            self.stats["h2d_bytes"] += cols.nbytes + xv.nbytes + zv.nbytes
        else:
            # zero movers: a shape-(0,) packet keeps the scatter an
            # in-program no-op under its own (bounded) compile key
            cols = np.zeros(0, np.int32)
            xv = zv = np.zeros(0, np.float32)
        self.stats["delta_flushes"] += 1
        sub = self._h2d("sub", np.array(self._subscribed), replicated=True)
        _T.lap("aoi.stage", _ts)
        _tk = _T.t()
        DC.record()
        out = self._sharded_step(len(cols))(
            self.prev, *scratch,
            self._dxs, self._dzs,
            self._h2d("r", self._hr), self._h2d("act", self._hact),
            self._dxr, self._dzr,
            self._h2d("act_all", self._hact, replicated=True),
            sub, cols, xv, zv)
        (new, chg, g_vals, g_nv, g_lane, g_csel, rowb, bitpos, woff,
         esc_rows, exc_gidx, exc_chg, exc_new, scalars,
         self._dxs, self._dzs, self._dxr, self._dzr) = out
        _T.lap("aoi.kernel", _tk)
        _T.lap("aoi.fused", _tk)
        self.prev = new
        scalars.copy_to_host_async()
        pf = None
        if self._subscribed:
            mc = self._max_chunks
            ndp = min(mc, self._pred[0])
            escp = min(self._max_gaps, self._pred[1])
            excp = min(self._max_exc, self._pred[2])
            slices = []
            for d in range(self.n_dev):
                sl = (rowb[d * mc:d * mc + ndp],
                      bitpos[d * mc:d * mc + ndp],
                      woff[d * mc:d * mc + ndp],
                      esc_rows[d * self._max_gaps:
                               d * self._max_gaps + escp],
                      exc_gidx[d * self._max_exc:d * self._max_exc + excp],
                      exc_chg[d * self._max_exc:d * self._max_exc + excp],
                      exc_new[d * self._max_exc:d * self._max_exc + excp])
                for a in sl:
                    a.copy_to_host_async()
                slices.append(sl)
            pf = (ndp, escp, excp, slices)
        self.stats["fused_dispatches"] += 1
        self.perf["stage_s"] += time.perf_counter() - t0
        self._sched = ("rec", {
            "caps": (self._max_chunks, self._kcap, self._max_gaps,
                     self._max_exc),
            "key": key,
            "scratch": (chg, g_vals, g_nv, g_lane, g_csel),
            "streams": (rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
                        exc_new),
            "scalars": scalars, "prefetch": pf})
        return True

    def _harvest(self, rec) -> None:  # gwlint: allow[host-sync] -- THE per-tick drain point: harvests kernel outputs once per flush
        c = self.capacity
        cl = self.c_local
        mc, kcap, mg, mx = rec["caps"]
        chunk_base = cl * self.W // _LANES  # chunks per chip
        (chg, g_vals, g_nv, g_lane, g_csel) = rec["scratch"]
        (rowb, bitpos, woff, esc_rows, exc_gidx, exc_chg,
         exc_new) = rec["streams"]
        faults.check("aoi.fetch")  # stallable: a delayed host sync
        t0 = time.perf_counter()
        _tf = _T.t()
        scal_h = faults.filter("aoi.scalars",
                               np.asarray(rec["scalars"]))  # [n_dev, 5]
        poisoned = False
        nw = cl * self.W  # words per chip
        if not ((scal_h >= 0).all()
                and (scal_h[:, 0] <= chunk_base).all()
                and (scal_h[:, 1] <= _LANES).all()
                and (scal_h[:, 2] <= chunk_base).all()
                and (scal_h[:, 3] <= nw).all()
                and (scal_h[:, 4] <= nw).all()):
            # garbage control scalars: distrust the encoded streams and
            # recover every chip from its raw diff grid (no cap growth off
            # corrupted values).  No other dispatch intervenes between the
            # phases (one bucket per space), so self.prev still holds THIS
            # tick's new words
            from ..utils import gwlog

            self.stats["poisoned"] += 1
            gwlog.logger("gw.aoi").warning(
                "row-shard AOI control scalars failed validation (%r); "
                "recovering the tick from the raw diff grids",
                scal_h.tolist())
            poisoned = True
        self.perf["fetch_s"] += time.perf_counter() - t0
        _T.lap("aoi.fetch", _tf)
        pf = rec["prefetch"]
        all_c, all_e, all_g = [], [], []
        grew = False
        peak = [0, 0, 0]
        peak_mcc = 0
        for d in range(self.n_dev):
            if poisoned:
                t0 = time.perf_counter()
                _tf = _T.t()
                lo = d * cl
                chg_h = np.asarray(chg[lo:lo + cl]).reshape(-1)
                gidx = np.nonzero(chg_h)[0]
                chg_vals = chg_h[gidx]
                new_h = np.asarray(self.prev[lo:lo + cl]).reshape(-1)
                ent_vals = chg_vals & new_h[gidx]
                self.perf["fetch_s"] += time.perf_counter() - t0
                _T.lap("aoi.fetch", _tf)
                all_c.append(chg_vals)
                all_e.append(ent_vals)
                all_g.append(np.asarray(gidx, np.int64)
                             + d * chunk_base * _LANES)
                continue
            nd, mcc, base_row, n_esc, exc_n = (int(v) for v in scal_h[d])
            if nd == 0 and exc_n == 0:
                continue
            t0 = time.perf_counter()
            _tf = _T.t()
            if nd > mc or mcc > kcap:
                # incomplete stream: recover from this chip's raw diff grid
                lo = d * cl
                if self.paged:
                    # paged absorber: compact the kept grids into pages
                    # on device and fetch only the used prefix -- no cap
                    # growth, no recompile, decode_overflow stays 0
                    chg_vals, ent_vals, gidx = _paged_absorb_chip(
                        self, chg[lo:lo + cl], self.prev[lo:lo + cl],
                        self.W)
                    self.perf["fetch_s"] += time.perf_counter() - t0
                    _T.lap("aoi.fetch", _tf)
                else:
                    self._max_chunks = max(self._max_chunks, 2 * nd)
                    self._kcap = min(max(self._kcap, 2 * mcc), _LANES)
                    self.stats["decode_overflow"] += 1
                    grew = True
                    chg_h = np.asarray(chg[lo:lo + cl]).reshape(-1)
                    new_h = np.asarray(self.prev[lo:lo + cl]).reshape(-1)
                    gidx = np.nonzero(chg_h)[0]
                    chg_vals = chg_h[gidx]
                    ent_vals = chg_vals & new_h[gidx]
                    self.perf["fetch_s"] += time.perf_counter() - t0
                    _T.lap("aoi.fetch", _tf)
            elif n_esc > mg or exc_n > mx:
                # encode overflow: rebuild from the kept chunk grids.  In
                # paged mode this is a counted spill (the chunk grids ARE
                # the compact recovery source, bounded by mc rows), with
                # no cap growth so the compile key never churns.
                if self.paged:
                    self.stats["page_spills"] += 1
                else:
                    self._max_gaps = max(mg, 2 * n_esc)
                    self._max_exc = max(mx, 2 * exc_n)
                    self.stats["decode_overflow"] += 1
                    grew = True
                lo = d * mc
                vh = np.asarray(g_vals[lo:lo + mc])
                nh = np.asarray(g_nv[lo:lo + mc])
                lh = np.asarray(g_lane[lo:lo + mc])
                ch = np.asarray(g_csel[lo:lo + mc])
                valid = lh >= 0
                chg_vals = vh[valid]
                ent_vals = chg_vals & nh[valid]
                gidx = (ch[:, None].astype(np.int64) * _LANES + lh)[valid]
                self.perf["fetch_s"] += time.perf_counter() - t0
                _T.lap("aoi.fetch", _tf)
            else:
                if pf is not None and pf[0] >= nd and pf[1] >= n_esc \
                        and pf[2] >= exc_n:
                    hb = [np.asarray(a) for a in pf[3][d]]
                else:
                    nds = max(nd, 1)
                    hb = [np.asarray(a) for a in (
                        rowb[d * mc:d * mc + nds],
                        bitpos[d * mc:d * mc + nds],
                        woff[d * mc:d * mc + nds],
                        esc_rows[d * mg:d * mg + max(n_esc, 1)],
                        exc_gidx[d * mx:d * mx + max(exc_n, 1)],
                        exc_chg[d * mx:d * mx + max(exc_n, 1)],
                        exc_new[d * mx:d * mx + max(exc_n, 1)])]
                self.perf["fetch_s"] += time.perf_counter() - t0
                _T.lap("aoi.fetch", _tf)
                t0 = time.perf_counter()
                _td = _T.t()
                chg_vals, ent_vals, gidx = EV.decode_row_stream(
                    hb[0], hb[1], hb[2].astype(np.uint16), base_row, nd,
                    _LANES, hb[3], hb[4], hb[5], hb[6])
                self.perf["decode_s"] += time.perf_counter() - t0
                _T.lap("aoi.diff", _td)
            peak = [max(peak[0], nd), max(peak[1], n_esc),
                    max(peak[2], exc_n)]
            peak_mcc = max(peak_mcc, mcc)
            all_c.append(chg_vals)
            all_e.append(ent_vals)
            all_g.append(np.asarray(gidx, np.int64) + d * chunk_base * _LANES)
        if grew:
            self._step_cache.clear()
            self._scratch.clear()
            self._caps.reset_after_growth()
        elif not poisoned:  # poisoned peaks are zeros, not observations
            shrink = self._caps.observe(peak[0], peak_mcc,
                                        self._max_chunks, self._kcap)
            if shrink is not None:
                self._max_chunks, self._kcap = shrink
                self._step_cache.clear()
                self._scratch.clear()
        self._pred = (
            max(512, min(mc, -(-(peak[0] * 5 // 4) // 128) * 128)),
            max(64, -(-(peak[1] + 1) * 3 // 2 // 64) * 64),
            max(256, -(-(peak[2] + 1) * 5 // 4 // 256) * 256),
        )
        t0 = time.perf_counter()
        _te = _T.t()
        empty = np.empty((0, 2), np.int32)
        if all_c:
            # fan-out through the bucket's emit path (C++ bit expansion
            # when emit="native"; bit-exact either way)
            pe, pl = _emit_expand(
                self, np.concatenate(all_c), np.concatenate(all_e),
                np.concatenate(all_g), 1)
            e = pe[:, 1:] if len(pe) else empty
            l = pl[:, 1:] if len(pl) else empty
        else:
            e = l = empty
        pend = self._events.get(0)
        if pend is not None:
            e = np.concatenate([pend[0], e])
            l = np.concatenate([pend[1], l])
        self._events[0] = (e, l)
        if rec["key"] == (self._max_chunks, self._kcap):
            self._scratch.setdefault(rec["key"], rec["scratch"])
        self.perf["emit_s"] += time.perf_counter() - t0
        _T.lap("aoi.emit", _te)

    # -- fault recovery (docs/robustness.md): no standing mirror at this
    # size, so the durable old state is reconstructed on demand -- the
    # set_prev seed if one is live (kept under an active plan), else the
    # predicate of the pre-tick shadows (exact: prev always equals the
    # predicate of the last stepped inputs, and clear_entity keeps the
    # shadows consistent).  The recovered tick publishes same-tick (this
    # bucket's flush is synchronous) and _host_prev carries the state until
    # _ensure_prev re-uploads it.

    def reset_calc_chain(self) -> None:
        """Re-arm the device calculator after fallback (operator action --
        demotion is sticky so a flapping device cannot oscillate)."""
        self._calc_level = 0
        self.stats["calc_level"] = 0
        # prev rebuilds lazily from _host_prev at the next _ensure_prev

    def _old_prev_host(self) -> np.ndarray:
        """The pre-tick interest words, reconstructed host-side."""
        if self._seed_prev is not None:
            old = self._seed_prev.copy()
        elif self._cur_old is not None:
            ox, oz, orr, oact = self._cur_old
            old = _packed_predicate(ox, oz, orr, oact)
        else:
            old = np.zeros((self.capacity, self.W), np.uint32)
        # land any clears still queued for the device (idempotent: the
        # predicate of shadows already excludes cleared entities)
        for ent in set(self._pending_clear):
            old[ent] = 0
            w, b = P.word_bit_for_column(ent, self.capacity)
            old[:, w] &= np.uint32(~(np.uint32(1) << np.uint32(b))
                                   & 0xFFFFFFFF)
        self._pending_clear.clear()
        return old

    def _recover(self, e: BaseException) -> None:  # gwlint: allow[flush-phase] -- fault recovery: the device is gone, host sync is the point
        """Device fault mid-flush: recompute the faulted tick host-side
        (bit-exact) and drop all device state."""
        from ..utils import gwlog

        self.stats["rebuilds"] += 1
        # kernel-phase faults demote outright; at harvest time the seam
        # cannot tell a kernel error from a transfer fault (async dispatch:
        # both surface at the blocking fetch), so the decision keys off the
        # exception class (_kernelish_fault)
        if (self._fault_phase == "kernel"
                or (self._fault_phase == "harvest" and _kernelish_fault(e))) \
                and self._calc_level < 2:
            self._calc_level += 1
            self.stats["fallbacks"] += 1
            self.stats["calc_level"] = self._calc_level
        gwlog.logger("gw.aoi").warning(
            "row-shard AOI bucket (cap %d) device fault during %s: %s -- "
            "recovering tick on host (calc level %d)",
            self.capacity, self._fault_phase, e, self._calc_level)
        # _dispatch_device restages BEFORE the device seams, so at fault time
        # the tick may already live in the shadows (_tick_inflight) rather
        # than in _staged -- both mean "a tick's events must be recovered"
        inflight = self._tick_inflight
        staged = inflight or bool(self._staged)
        if staged:
            if not inflight:
                self._restage_shadows()
            old_prev = self._old_prev_host()
        else:
            # maintenance-only flush: nothing stepped, so there are no
            # events to recover -- only the state survives.  The current
            # shadows ARE the last stepped inputs; _old_prev_host derives
            # the pre-fault words from them (or the set_prev seed) and
            # lands any queued clears
            self._cur_old = (self._hx, self._hz, self._hr, self._hact)
            old_prev = self._old_prev_host()
        # drop device state; _ensure_prev re-uploads _host_prev next flush
        self.prev = None
        self._dxs = self._dzs = self._dxr = self._dzr = None
        self._xz_stale = True
        self._h2d_cache.clear()
        self._scratch.clear()
        self._page_free = None  # device-resident free list died with it
        if staged:
            self._host_tick(old_prev)
        else:
            self._host_prev = old_prev
            self._seed_prev = None
            self._cur_old = None
        self._tick_inflight = False

    def _host_tick(self, old_prev: np.ndarray) -> None:
        """One tick on the host from the durable copies, bit-exact with the
        sharded step: the global flat word order equals the per-chip
        extraction order after the chip-offset shift."""
        self.stats["host_ticks"] += 1
        _th = _T.t()
        new = _packed_predicate(self._hx, self._hz, self._hr, self._hact)
        empty = np.empty((0, 2), np.int32)
        if self._subscribed:
            chg = new ^ old_prev
            flat = chg.reshape(-1)
            gidx = np.nonzero(flat)[0]
            chg_vals = flat[gidx]
            ent_vals = chg_vals & new.reshape(-1)[gidx]
            pe, pl = _emit_expand(self, chg_vals, ent_vals, gidx, 1)
            e = pe[:, 1:] if len(pe) else empty
            l = pl[:, 1:] if len(pl) else empty
        else:
            e = l = empty
        pend = self._events.get(0)
        if pend is not None:
            e = np.concatenate([pend[0], e])
            l = np.concatenate([pend[1], l])
        self._events[0] = (e, l)
        self._host_prev = new
        self._seed_prev = None
        self._cur_old = None
        _T.lap("aoi.host_tick", _th)

    def _dispatch_oracle(self) -> None:
        """Level-2 fallback dispatch: the device is out of the loop
        entirely; _host_prev is the authoritative state.  Maintenance and
        restaging run now, the host compute parks for harvest() so it
        overlaps other buckets' device work under the scheduler."""
        if self._host_prev is None:
            self._host_prev = np.zeros((self.capacity, self.W), np.uint32)
        if self._pending_clear:
            # the device maintenance scatter, applied to the host copy
            for ent in set(self._pending_clear):
                self._host_prev[ent] = 0
                w, b = P.word_bit_for_column(ent, self.capacity)
                self._host_prev[:, w] &= np.uint32(
                    ~(np.uint32(1) << np.uint32(b)) & 0xFFFFFFFF)
            self._pending_clear.clear()
        if not self._staged:
            return
        self._restage_shadows()
        old_prev = self._seed_prev if self._seed_prev is not None \
            else self._host_prev
        self._sched = ("oracle", old_prev)

    # -- state carry / lazy derivation --------------------------------------
    def get_prev(self, slot: int) -> np.ndarray:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        self.flush()
        if self.prev is None:
            if self._host_prev is not None:  # device down: host copy rules
                return np.array(self._host_prev, copy=True)
            return np.zeros((self.capacity, self.W), np.uint32)
        self.full_roundtrips += 1
        return np.asarray(self.prev)

    def set_prev(self, slot: int, words: np.ndarray) -> None:
        self.flush()
        words = np.ascontiguousarray(words, np.uint32)
        if self._calc_level >= 2 or self.prev is None:
            # device down: the words land host-side; _ensure_prev uploads
            # them if the calculator chain re-arms
            self._host_prev = words.copy()
            self._seed_prev = None
            return
        self.full_roundtrips += 1
        if self._ft:
            # the seed is the ONLY durable copy of carried-in state until
            # the next step (prev != predicate(shadows) in between); keep
            # it host-side while a fault plan is active
            self._seed_prev = words.copy()
        self.prev = self.mesh.device_put(words)

    # -- live migration & chip-loss failover (docs/robustness.md) ----------

    def _mark_evacuating(self) -> None:
        """The shard's devices are LOST (faults.DeviceLost): never touch
        them again.  Host-oracle mode keeps the bucket serving bit-exact
        ticks from (_host_prev, shadows) until the engine rebuilds the
        space onto a fresh bucket at the end of the current flush."""
        self._evacuating = True
        self._calc_level = 2
        self.stats["calc_level"] = 2

    def export_snapshot(self, slot: int) -> dict:  # gwlint: allow[host-sync] -- migration snapshot, off the steady tick path
        """Live-migration wire image of THE slot: the 1-D input shadows as
        a delta-staging packet + the previous-tick interest words (see
        _TPUBucket.export_snapshot; this bucket's flush is synchronous, so
        there is no pipeline to drain)."""
        return _build_snapshot(self.capacity, self._hx, self._hz, self._hr,
                               self._hact, self._subscribed,
                               self.get_prev(slot))

    def import_snapshot(self, slot: int, snap: dict) -> None:  # gwlint: allow[host-sync] -- migration replay, off the steady tick path
        """Replay a migration snapshot onto this bucket (see
        _TPUBucket.import_snapshot; shadows here are 1-D, one space)."""
        if snap["capacity"] != self.capacity:
            raise ValueError(
                f"snapshot capacity {snap['capacity']} != bucket "
                f"capacity {self.capacity}")
        x, z = _unpack_positions(snap)
        self._hx[:] = x
        self._hz[:] = z
        self._hr[:] = snap["r"]
        self._hact[:] = snap["act"]
        self.set_subscribed(slot, snap["sub"])
        self._xz_stale = True  # device x/z copies diverged: full restage
        self._h2d_cache.clear()
        self.set_prev(slot, snap["words"])
        if self._ft:
            # set_prev parked the words host-side (device state is lazy)
            # and dropped the seed; under an active plan the seed is the
            # exact recovery base for a fault on the first post-import
            # tick (prev != predicate(shadows) until that tick lands)
            self._seed_prev = np.ascontiguousarray(snap["words"], np.uint32)

    def evacuate(self) -> dict[int, dict]:
        """Snapshot the (single) occupied slot for rebuild on surviving
        devices (the engine drives this after a DeviceLost recovery
        marked the bucket evacuating)."""
        live = sorted(set(range(self.n_slots)) - set(self._free))
        return {slot: self.export_snapshot(slot) for slot in live}

    def peek_words(self, slot: int):
        return None  # no host mirror at this size; use derive_row/derive_col

    def derive_row(self, slot: int, entity_slot: int) -> np.ndarray:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        """One observer's interest words [W] -- a 16 KB on-demand fetch."""
        self.flush()
        if self.prev is None:
            if self._host_prev is not None:  # device down: host copy rules
                return np.array(self._host_prev[entity_slot], copy=True)
            return np.zeros(self.W, np.uint32)
        return np.asarray(self.prev[entity_slot])

    def derive_col(self, slot: int, entity_slot: int) -> np.ndarray:  # gwlint: allow[host-sync] -- parity/debug accessor, off the tick path
        """Row indices of observers interested in ``entity_slot`` (the
        packed column), from one [C] word-column fetch."""
        self.flush()
        w, b = P.word_bit_for_column(entity_slot, self.capacity)
        if self.prev is None:
            if self._host_prev is None:
                return np.empty(0, np.int64)
            colw = self._host_prev[:, w]  # device down: host copy rules
        else:
            colw = np.asarray(self.prev[:, w])
        return np.nonzero(colw & (np.uint32(1) << np.uint32(b)))[0]
