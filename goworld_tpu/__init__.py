"""goworld_tpu -- a TPU-native distributed game-server framework.

A ground-up re-design of the capabilities of GoWorld (studied at
/root/reference; see SURVEY.md) for TPU: the per-Space AOI (area-of-interest)
visibility pass runs as a batched JAX/Pallas kernel with Spaces sharded over
chips, while the entity runtime, dispatcher fabric, gates, persistence and ops
tooling are host-side components mirroring the reference's architecture.
"""

__version__ = "0.1.0"
