"""Headless load-test bot client (reference: examples/test_client -- N bots
speaking the full client protocol with strict assertions and a per-op
latency profiler).

    python examples/test_client.py --gate 127.0.0.1:17001 -N 50 \
        --duration 30 --strict
"""

from __future__ import annotations

import argparse
import random
import statistics
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from goworld_tpu.client import GameClientConnection


class Bot(threading.Thread):
    def __init__(self, addr, idx, duration, strict, stats, transport="tcp",
                 tls=False):
        super().__init__(daemon=True)
        self.addr = addr
        self.transport = transport
        self.tls = tls
        self.idx = idx
        self.duration = duration
        self.strict = strict
        self.stats = stats
        self.ok = False
        self.error = ""

    def run(self):
        try:
            self._run()
            self.ok = True
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            if self.strict:
                raise

    def _assert(self, cond, msg):
        if self.strict:
            assert cond, f"bot{self.idx}: {msg}"

    def _run(self):
        rng = random.Random(self.idx)
        t0 = time.perf_counter()
        c = GameClientConnection(self.addr, transport=self.transport, tls=self.tls)
        self._assert(
            c.wait_for(lambda c: c.player is not None, 15), "no boot entity"
        )
        self.stats.record("login", time.perf_counter() - t0)
        c.call_player("enter_game", f"bot{self.idx}")
        self._assert(
            c.wait_for(lambda c: c.player and c.player.attrs.get("name") == f"bot{self.idx}", 15),
            "enter_game attr never mirrored",
        )
        x, z = rng.uniform(0, 200), rng.uniform(0, 200)
        deadline = time.time() + self.duration
        last_hb = 0.0
        while time.time() < deadline:
            x += rng.uniform(-5, 5)
            z += rng.uniform(-5, 5)
            t = time.perf_counter()
            c.send_position(x, 0.0, z)
            c.poll(0.05)
            self.stats.record("tick", time.perf_counter() - t)
            if time.time() - last_hb > 5:
                c.heartbeat()
                last_hb = time.time()
            if self.strict and c.player is not None:
                for e in c.entities.values():
                    assert e.id, "mirror with empty id"
        c.close()


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.samples: dict[str, list[float]] = {}

    def record(self, op, dt):
        with self.lock:
            self.samples.setdefault(op, []).append(dt)

    def dump(self):
        for op, xs in sorted(self.samples.items()):
            ms = [x * 1e3 for x in xs]
            print(
                f"{op:8s} n={len(ms):<7d} avg={statistics.mean(ms):8.2f}ms "
                f"p95={statistics.quantiles(ms, n=20)[-1] if len(ms) > 20 else max(ms):8.2f}ms "
                f"max={max(ms):8.2f}ms"
            )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--gate", default="127.0.0.1:17001",
        help="gate address, or a comma-separated list -- bots spread over "
             "them round-robin (reference: ClientBot picks any gate, "
             "ClientBot.go:81-84)",
    )
    ap.add_argument("-N", type=int, default=10)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--strict", action="store_true")
    ap.add_argument("--transport", default="tcp", choices=["tcp", "ws", "kcp"])
    ap.add_argument("--tls", action="store_true")
    args = ap.parse_args()
    addrs = []
    for part in args.gate.split(","):
        part = part.strip()
        if not part:
            continue
        host, port = part.rsplit(":", 1)
        addrs.append((host, int(port)))
    stats = Stats()
    bots = [Bot(addrs[i % len(addrs)], i, args.duration, args.strict, stats,
                transport=args.transport, tls=args.tls) for i in range(args.N)]
    for b in bots:
        b.start()
        time.sleep(0.01)
    for b in bots:
        b.join(args.duration + 60)
    failed = [b for b in bots if not b.ok]
    stats.dump()
    print(f"{len(bots) - len(failed)}/{len(bots)} bots OK")
    for b in failed[:5]:
        print(f"  bot{b.idx} failed: {b.error}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
