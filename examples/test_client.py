"""Headless load-test bot client (reference: examples/test_client -- N bots
speaking the full client protocol with strict assertions and a per-op
latency profiler, ClientBot.go / ClientEntity.go / profile.go:19-51).

Pairs with the ``examples/unity_demo`` game script (its Avatar exposes the
``enter_game``/``move`` RPC surface the bots drive); ``examples/test_game``
is the in-process everything-at-once scene exercised by tests/test_examples.

    python examples/test_client.py --gate 127.0.0.1:17001 -N 50 \
        --duration 30 --strict --profile 1

Strict mode layers three oracles on the live cluster:
  * protocol invariants inside the client mirror (goworld_tpu.client:
    duplicate creates, destroys for unknown mirrors, handshake reuse);
  * attr-mirror invariants: the bot's own writes must round-trip through
    the server's delta stream onto its player mirror;
  * cross-bot AOI visibility: two bots steadily within the interest radius
    must each mirror the other's player entity; steadily far apart they
    must not (the interest sets ARE the product -- this asserts them from
    the outside, against ground-truth positions the bots themselves chose).
"""

from __future__ import annotations

import argparse
import math
import random
import statistics
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from goworld_tpu.client import GameClientConnection

AOI_DISTANCE = 100.0  # unity_demo scene radius (examples/unity_demo/server.py)
# visibility-oracle hysteresis: only assert when a pair has been steadily
# inside (or outside) these bounds for the full grace window, so in-flight
# enters/leaves and sync latency can't fake a violation
SEE_DIST = 0.7 * AOI_DISTANCE
UNSEE_DIST = 1.8 * AOI_DISTANCE
GRACE_S = 3.0


class SharedTruth:
    """Ground-truth positions each bot reports about itself; the visibility
    oracle reads it to decide which pairs MUST (not) see each other."""

    def __init__(self):
        self.lock = threading.Lock()
        self.pos: dict[int, tuple] = {}  # bot idx -> (player_eid, x, z)

    def report(self, idx, eid, x, z):
        with self.lock:
            self.pos[idx] = (eid, x, z)

    def retract(self, idx):
        """A finished/failed bot must leave the oracle's world: its entity
        is (being) destroyed server-side, so judging against its last
        position would hard-fail every nearby surviving bot."""
        with self.lock:
            self.pos.pop(idx, None)

    def snapshot(self):
        with self.lock:
            return dict(self.pos)


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.samples: dict[str, list[float]] = {}
        self.window: dict[str, list[float]] = {}
        self.counters: dict[str, int] = {}

    def record(self, op, dt):
        with self.lock:
            self.samples.setdefault(op, []).append(dt)
            self.window.setdefault(op, []).append(dt)

    def count(self, name, n):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def dump_window(self):
        with self.lock:
            win, self.window = self.window, {}
        parts = []
        for op, xs in sorted(win.items()):
            ms = [x * 1e3 for x in xs]
            parts.append(f"{op} n={len(ms)} avg={statistics.mean(ms):.1f}ms "
                         f"max={max(ms):.1f}ms")
        if parts:
            print("[profile] " + "  ".join(parts), flush=True)

    def dump(self):
        for op, xs in sorted(self.samples.items()):
            ms = [x * 1e3 for x in xs]
            p95 = (statistics.quantiles(ms, n=20)[-1]
                   if len(ms) > 20 else max(ms))
            print(f"{op:8s} n={len(ms):<7d} avg={statistics.mean(ms):8.2f}ms "
                  f"p95={p95:8.2f}ms max={max(ms):8.2f}ms")
        for name, n in sorted(self.counters.items()):
            print(f"{name}: {n}")


class Bot(threading.Thread):
    def __init__(self, addr, idx, duration, strict, stats, truth,
                 transport="tcp", tls=False):
        super().__init__(daemon=True)
        self.addr = addr
        self.transport = transport
        self.tls = tls
        self.idx = idx
        self.duration = duration
        self.strict = strict
        self.stats = stats
        self.truth = truth
        self.ok = False
        self.error = ""
        self.visibility_checks = 0
        self._pair_state: dict[int, tuple] = {}  # oidx -> (zone, eid, since)
        self._oracle_pause_until = 0.0

    def run(self):
        try:
            self._run()
            self.ok = True
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            if self.strict:
                raise
        finally:
            self.truth.retract(self.idx)

    def _assert(self, cond, msg):
        if self.strict:
            assert cond, f"bot{self.idx}: {msg}"

    def _check_visibility(self, c, my_x, my_z, now):
        """Cross-bot AOI oracle: a pair STEADILY in the near (far) zone for
        GRACE_S must (must not) be mirrored.  The per-pair zone tracker
        restarts its clock on every zone change, so fast approaches don't
        assert before the server's enter event can possibly have arrived."""
        if now < self._oracle_pause_until:
            self._pair_state.clear()
            return
        for oidx, (oeid, ox, oz) in self.truth.snapshot().items():
            if oidx == self.idx:
                continue
            d = math.hypot(ox - my_x, oz - my_z)
            zone = "near" if d < SEE_DIST else (
                "far" if d > UNSEE_DIST else "mid")
            prev = self._pair_state.get(oidx)
            if prev is None or prev[0] != zone or prev[1] != oeid:
                self._pair_state[oidx] = (zone, oeid, now)
                continue
            if now - prev[2] < GRACE_S or zone == "mid":
                continue
            if zone == "near":
                self._assert(
                    oeid in c.entities,
                    f"bot{oidx}'s player {oeid} steadily at distance "
                    f"{d:.0f} (< {SEE_DIST:.0f}) for {GRACE_S}s, "
                    f"never mirrored",
                )
            else:
                self._assert(
                    oeid not in c.entities,
                    f"bot{oidx}'s player {oeid} steadily at distance "
                    f"{d:.0f} (> {UNSEE_DIST:.0f}) for {GRACE_S}s, "
                    f"still mirrored",
                )
            self.visibility_checks += 1

    def _run(self):
        rng = random.Random(self.idx)
        t0 = time.perf_counter()
        c = GameClientConnection(self.addr, transport=self.transport,
                                 tls=self.tls, strict=self.strict)
        self._assert(
            c.wait_for(lambda c: c.player is not None, 15), "no boot entity"
        )
        self.stats.record("login", time.perf_counter() - t0)
        c.call_player("enter_game", f"bot{self.idx}")
        # attr-mirror invariant: our own write must round-trip via the
        # server's delta stream
        self._assert(
            c.wait_for(lambda c: c.player is not None
                       and c.player.attrs.get("name") == f"bot{self.idx}", 15),
            "enter_game attr never mirrored",
        )
        # wait to land in the real space (player re-created on space enter)
        time.sleep(0.5)
        c.poll(0.1)
        x, z = rng.uniform(0, 200), rng.uniform(0, 200)
        deadline = time.time() + self.duration
        last_hb = 0.0
        last_vis = 0.0
        last_rx = time.monotonic()
        while time.time() < deadline:
            dx, dz = rng.uniform(-5, 5), rng.uniform(-5, 5)
            x = min(max(x + dx, 0.0), 400.0)
            z = min(max(z + dz, 0.0), 400.0)
            t = time.perf_counter()
            c.send_position(x, 0.0, z)
            handled = c.poll(0.05)
            self.stats.record("tick", time.perf_counter() - t)
            now = time.monotonic()
            if handled:
                last_rx = now
            elif now - last_rx > 1.0:
                # the event stream is stalled (e.g. a hot reload froze the
                # games): visibility timing guarantees are void until the
                # server has also worked through the backlog of moves
                # queued while frozen, so park the oracle well past resume
                self._pair_state.clear()
                self._oracle_pause_until = now + 15.0
            if c.player is not None and len(c.entities) > 1:
                # >1 mirror means we left the nil space (the scene spawns
                # monsters next to every player) -- only then are we a
                # legitimate subject for the cross-bot visibility oracle
                self.truth.report(self.idx, c.player.id, x, z)
            if time.time() - last_hb > 5:
                c.heartbeat()
                last_hb = time.time()
            if self.strict and c.player is not None:
                for e in list(c.entities.values()):
                    assert e.id, "mirror with empty id"
                if now - last_vis > 1.0:
                    self._check_visibility(c, x, z, now)
                    last_vis = now
        for kind, n in c.anomalies.items():
            self.stats.count(f"anomaly.{kind}", n)
        c.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--gate", default="127.0.0.1:17001",
        help="gate address, or a comma-separated list -- bots spread over "
             "them round-robin (reference: ClientBot picks any gate, "
             "ClientBot.go:81-84)",
    )
    ap.add_argument("-N", type=int, default=10)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--strict", action="store_true")
    ap.add_argument("--profile", type=float, default=0.0,
                    help="dump per-op latency every N seconds (reference: "
                         "test_client profile.go:19-51)")
    ap.add_argument("--transport", default="tcp", choices=["tcp", "ws", "kcp"])
    ap.add_argument("--tls", action="store_true")
    args = ap.parse_args()
    addrs = []
    for part in args.gate.split(","):
        part = part.strip()
        if not part:
            continue
        host, port = part.rsplit(":", 1)
        addrs.append((host, int(port)))
    stats = Stats()
    truth = SharedTruth()
    bots = [Bot(addrs[i % len(addrs)], i, args.duration, args.strict, stats,
                truth, transport=args.transport, tls=args.tls)
            for i in range(args.N)]
    for b in bots:
        b.start()
        time.sleep(0.01)
    if args.profile > 0:
        stop = time.monotonic() + args.duration + 5
        while time.monotonic() < stop and any(b.is_alive() for b in bots):
            time.sleep(args.profile)
            stats.dump_window()
    for b in bots:
        b.join(args.duration + 60)
    failed = [b for b in bots if not b.ok]
    stats.dump()
    vis = sum(b.visibility_checks for b in bots)
    if args.strict:
        print(f"visibility checks: {vis}")
    print(f"{len(bots) - len(failed)}/{len(bots)} bots OK")
    for b in failed[:5]:
        print(f"  bot{b.idx} failed: {b.error}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
