"""unity_demo equivalent: the baseline AOI scene.

Reference: /root/reference/examples/unity_demo -- a space with AOI distance
100, players with client-synced positions, monsters with AI that chases
players via their interest sets, a SpaceService capping avatars per space.
"""

from goworld_tpu.engine.entity import Entity
from goworld_tpu.engine.rpc import OWN_CLIENT, rpc
from goworld_tpu.engine.space import Space
from goworld_tpu.engine.vector import Vector3
from goworld_tpu.services import ServiceManager

AOI_DISTANCE = 100.0
MAX_AVATARS_PER_SPACE = 100


class MySpace(Space):
    def on_space_init(self):
        self.enable_aoi(AOI_DISTANCE)

    def on_entity_enter_space(self, e):
        if e.type_name == "Player":
            # monsters ~ 2x players (reference: MySpace.go:43-47)
            mgr = self.manager
            n_players = sum(
                1 for x in self.entities if x.type_name == "Player"
            )
            n_monsters = sum(
                1 for x in self.entities if x.type_name == "Monster"
            )
            while n_monsters < 2 * n_players:
                mgr.create(
                    "Monster",
                    space=self,
                    pos=Vector3(
                        e.position.x + 30 + 10 * n_monsters, 0, e.position.z + 30
                    ),
                )
                n_monsters += 1


class Player(Entity):
    use_aoi = True
    aoi_distance = AOI_DISTANCE
    all_client_attrs = frozenset({"name", "lv", "hp"})
    client_attrs = frozenset({"exp"})
    persistent_attrs = frozenset({"name", "lv", "hp", "exp"})
    persistent = True

    def on_created(self):
        self.attrs.set_default("name", "noname")
        self.attrs.set_default("lv", 1)
        self.attrs.set_default("hp", 100)
        self.set_client_syncing(True)

    @rpc(expose=OWN_CLIENT)
    def enter_game(self, name):
        self.attrs.set("name", name)
        self.request_space()

    def request_space(self):
        # SpaceService may not be claimed yet right after boot; retry until
        # the srvdis registration lands
        svc = self._runtime().game.services
        if self.space is None or self.space.is_nil:
            if not svc.call_service("SpaceService", "enter_space", self.id):
                self.add_callback(0.5, "request_space")

    @rpc(expose=OWN_CLIENT)
    def whoami(self):
        self.call_client("on_whoami", self.attrs.get_str("name"))

    @rpc
    def do_enter_space(self, space_id):
        self.enter_space(space_id, Vector3(0, 0, 0))


class Monster(Entity):
    use_aoi = True
    aoi_distance = AOI_DISTANCE
    all_client_attrs = frozenset({"name"})

    def on_created(self):
        self.attrs.set("name", "monster")
        self.add_timer(0.1, "ai_tick")

    def ai_tick(self):
        # neighbors() is the lazy-aware accessor: a hook-less clientless
        # entity's interests live in the calculator's packed words
        prey = [e for e in self.neighbors() if e.type_name == "Player"]
        if not prey:
            return
        target = min(prey, key=lambda p: p.position.distance_to(self.position))
        d = target.position.sub(self.position)
        dist = d.distance_to(Vector3())
        if dist > 3.0:
            step = d.normalized().scale(2.0)
            self.set_position(self.position.add(step))
            self.set_yaw(d.dir_to_yaw())


class SpaceService(Entity):
    """Cluster singleton that places avatars into spaces, spinning up a new
    space when the current one is full (reference: unity_demo/SpaceService.go)."""

    def on_init(self):
        self.attrs.get_list("spaces")  # [space_id, ...]
        self.attrs.get_map("counts")   # space_id -> member count

    @rpc
    def enter_space(self, player_eid):
        game = self._runtime().game
        counts = self.attrs.get_map("counts")
        for sid in self.attrs.get_list("spaces"):
            if counts.get_int(sid) < MAX_AVATARS_PER_SPACE:
                counts.set(sid, counts.get_int(sid) + 1)
                game.call_entity(player_eid, "do_enter_space", sid)
                return
        sp = game.rt.entities.create_space("MySpace", kind=1)
        self.attrs.get_list("spaces").append(sp.id)
        counts.set(sp.id, 1)
        game.call_entity(player_eid, "do_enter_space", sp.id)


def setup(game):
    game.register_entity_type(MySpace)
    game.register_entity_type(Player)
    game.register_entity_type(Monster)
    services = ServiceManager(game)
    services.register(SpaceService)
    services.setup()
    game.services = services
