"""test_game equivalent (reference: examples/test_game -- the full engine
exercise: Avatar with filter props, MailService, OnlineService, pubsub
subscriptions, AOITester).  Used by the e2e suite as the "everything at
once" scene.
"""

from goworld_tpu.engine.entity import Entity
from goworld_tpu.engine.rpc import ALL_CLIENTS, OWN_CLIENT, rpc
from goworld_tpu.engine.space import Space
from goworld_tpu.engine.vector import Vector3
from goworld_tpu.ext.pubsub import PublishSubscribeService
from goworld_tpu.proto.msgtypes import FILTER_OP_EQ
from goworld_tpu.services import ServiceManager
from goworld_tpu.utils.asyncjobs import JobError

AOI_DISTANCE = 100.0


class TestSpace(Space):
    def on_space_init(self):
        self.enable_aoi(AOI_DISTANCE)


class OnlineService(Entity):
    """Tracks online avatars (reference: test_game/OnlineService.go)."""

    def on_init(self):
        self.attrs.get_map("online")  # eid -> name

    @rpc
    def check_in(self, eid, name):
        self.attrs.get_map("online").set(eid, name)

    @rpc
    def check_out(self, eid):
        online = self.attrs.get_map("online")
        if eid in online:
            online.delete(eid)

    @rpc
    def query_online(self, caller_eid):
        self.call_entity(
            caller_eid, "on_online_list",
            sorted(self.attrs.get_map("online").keys()),
        )


class MailService(Entity):
    """Store-and-forward mail through kvdb (reference: test_game/
    MailService.go writes mails through kvdb with ordered ids)."""

    def on_init(self):
        self.attrs.set_default("next_mail_id", 1)

    @rpc
    def send_mail(self, sender_name, target_eid, text):
        kv = self.kvdb
        if kv is None:
            return
        mail_id = self.attrs.get("next_mail_id")
        self.attrs.set("next_mail_id", mail_id + 1)
        key = f"mail${target_eid}${mail_id:010d}"
        kv.put(
            key, f"{sender_name}: {text}",
            callback=lambda _r, t=target_eid: self.call_entity(
                t, "on_mail_delivered", mail_id
            ),
        )

    @rpc
    def fetch_mails(self, caller_eid):
        kv = self.kvdb
        if kv is None:
            return

        def on_found(rows):
            if isinstance(rows, JobError):
                return
            self.call_entity(
                caller_eid, "on_mails", [v for _k, v in rows]
            )

        kv.find(f"mail${caller_eid}$", f"mail${caller_eid}%", on_found)


class Avatar(Entity):
    use_aoi = True
    aoi_distance = AOI_DISTANCE
    all_client_attrs = frozenset({"name"})
    client_attrs = frozenset({"mails_got"})
    persistent_attrs = frozenset({"name"})
    persistent = True

    def on_created(self):
        self.attrs.set_default("name", "anon")
        self.attrs.set_default("mails_got", 0)
        self.set_client_syncing(True)

    def on_client_connected(self):
        self._announce_online()
        self.set_filter_prop("team", "blue")

    @rpc
    def _announce_online(self):
        """check_in + subscribe, retried until the cluster singletons have
        been placed (service reconciliation is periodic, so a client that
        connects during cluster formation must not lose its check-in)."""
        svc = self.game.services if self.game else None
        if svc is None:
            return
        ok = svc.call_service(
            "OnlineService", "check_in", self.id, self.attrs.get("name")
        ) and svc.call_service(
            "PublishSubscribeService", "subscribe", self.id, "broadcast.*"
        )
        if not ok and self.client is not None:
            self.add_callback(0.5, "_announce_online")

    def on_destroy(self):
        svc = self.game.services if self.game else None
        if svc is not None:
            svc.call_service("OnlineService", "check_out", self.id)

    # -- space / aoi -------------------------------------------------------
    @rpc(expose=OWN_CLIENT)
    def join_scene(self):
        scene_id = self.game.srvmap.get("test_scene") if self.game else None
        if scene_id:
            self.enter_space(scene_id, Vector3(10.0, 0.0, 10.0))
        else:
            # scene not declared yet (cluster still forming): retry
            self.add_callback(0.5, "join_scene")

    @rpc(expose=OWN_CLIENT)
    def set_name(self, name):
        self.attrs.set("name", name)

    # -- mail --------------------------------------------------------------
    @rpc(expose=OWN_CLIENT)
    def mail_to(self, target_eid, text):
        svc = self.game.services if self.game else None
        if svc is not None:
            svc.call_service(
                "MailService", "send_mail",
                self.attrs.get("name"), target_eid, text,
            )

    @rpc(expose=OWN_CLIENT)
    def read_mails(self):
        svc = self.game.services if self.game else None
        if svc is not None:
            svc.call_service("MailService", "fetch_mails", self.id)

    @rpc
    def on_mail_delivered(self, mail_id):
        self.attrs.set("mails_got", self.attrs.get("mails_got") + 1)

    @rpc
    def on_mails(self, mails):
        self.call_client("mails", mails)

    # -- pubsub ------------------------------------------------------------
    @rpc(expose=OWN_CLIENT)
    def shout(self, text):
        svc = self.game.services if self.game else None
        if svc is not None:
            svc.call_service(
                "PublishSubscribeService", "publish",
                "broadcast.all", self.attrs.get("name"), text,
            )

    @rpc
    def on_published(self, subject, name, text):
        self.call_client("heard", subject, name, text)

    # -- online list -------------------------------------------------------
    @rpc(expose=OWN_CLIENT)
    def who_is_online(self):
        svc = self.game.services if self.game else None
        if svc is not None:
            svc.call_service("OnlineService", "query_online", self.id)

    @rpc
    def on_online_list(self, eids):
        self.call_client("online_list", eids)

    # -- filtered broadcast ------------------------------------------------
    @rpc(expose=OWN_CLIENT)
    def team_shout(self, text):
        self.call_filtered_clients(
            "team", FILTER_OP_EQ, "blue", "team_heard",
            self.attrs.get("name"), text,
        )


class AOITester(Entity):
    """Server-side AOI assertion entity (reference: test_game/AOITester.go):
    counts enter/leave callbacks and verifies symmetry on demand."""

    use_aoi = True
    aoi_distance = AOI_DISTANCE

    def on_created(self):
        self.enters = 0
        self.leaves = 0

    def on_enter_aoi(self, other):
        self.enters += 1

    def on_leave_aoi(self, other):
        self.leaves += 1

    @rpc
    def assert_consistent(self):
        assert self.enters >= self.leaves, (
            f"AOI leave without enter: {self.enters} < {self.leaves}"
        )
        assert len(self.interested_in) == self.enters - self.leaves, (
            "interest set out of sync with enter/leave events"
        )


def make_scene(game):
    """Game 1 creates the shared scene + declares it via srvdis."""
    sp = game.rt.entities.create_space("TestSpace", kind=1)
    game.declare_service("test_scene", sp.id)
    return sp


def setup(game):
    game.register_entity_type(TestSpace)
    game.register_entity_type(Avatar)
    game.register_entity_type(AOITester)
    services = ServiceManager(game)
    services.register(OnlineService)
    services.register(MailService)
    services.register(PublishSubscribeService)
    services.setup()
    game.services = services


def on_ready(game):
    if game.id == 1:
        make_scene(game)
