"""nil_game equivalent (reference: examples/nil_game -- the minimal game:
no custom spaces or entities beyond the implicit nil space; proves the
engine boots, reaches deployment readiness, and serves a boot entity)."""

from goworld_tpu.engine.entity import Entity
from goworld_tpu.engine.rpc import OWN_CLIENT, rpc


class NilBoot(Entity):
    """Minimal boot entity so clients can connect (the reference nil_game
    configures no boot entity at all; a ping surface makes it testable)."""

    @rpc(expose=OWN_CLIENT)
    def ping(self, x):
        self.call_client("pong", x)


def setup(game):
    game.register_entity_type(NilBoot)
