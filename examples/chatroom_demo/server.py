"""chatroom_demo equivalent (reference: examples/chatroom_demo -- account
register/login via KVDB, LoadEntityAnywhere + GiveClientTo handoff, room
switching broadcast via filtered client calls).

Flow (reference Account.go:20-121):
  * boot entity is an Account; client calls register(username, password)
    -> kvdb get/put ("password$<u>"), creates+saves an Avatar, stores
    "avatarID$<u>";
  * login(username, password) -> kvdb checks -> LoadEntityAnywhere(Avatar)
    -> call avatar "get_room" -> GiveClientTo(avatar);
  * avatar joins a chat room by setting its client filter prop "room" and
    says things via CallFilteredClients(room == X, "hear", ...).
"""

from goworld_tpu.engine.entity import Entity
from goworld_tpu.engine.rpc import OWN_CLIENT, rpc
from goworld_tpu.proto.msgtypes import FILTER_OP_EQ
from goworld_tpu.utils.asyncjobs import JobError


class Account(Entity):
    def on_created(self):
        self.logining = False

    @rpc(expose=OWN_CLIENT)
    def register(self, username, password):
        kv = self.kvdb
        if kv is None:
            self.call_client("show_error", "no kvdb attached")
            return

        def on_claimed(existing):
            if isinstance(existing, JobError):
                self.call_client("show_error", "server error")
                return
            if existing is not None:
                # get_or_put returned a prior value: the name was taken --
                # atomic on the ordered kvdb worker, so two simultaneous
                # registrations cannot both claim it
                self.call_client("show_error", "account already exists")
                return
            # create the avatar record (reference: CreateEntityLocally +
            # immediate destroy to force one save, Account.go:33-36)
            avatar = self.manager.create("Avatar")
            avatar.attrs.set("name", username)
            avatar_id = avatar.id
            game = self.game
            if game is not None and game.storage is not None:
                game.storage.save(
                    "Avatar", avatar_id, avatar.persistent_data()
                )
            avatar.destroy()
            kv.put(
                f"avatarID${username}", avatar_id,
                callback=lambda _r: self.call_client(
                    "show_info", "registered; please log in"
                ),
            )

        kv.get_or_put(f"password${username}", password, on_claimed)

    @rpc(expose=OWN_CLIENT)
    def login(self, username, password):
        if self.logining:
            return
        kv = self.kvdb
        if kv is None:
            self.call_client("show_error", "no kvdb attached")
            return
        self.logining = True

        def fail(msg):
            self.logining = False
            self.call_client("show_error", msg)

        def on_password(correct):
            if isinstance(correct, JobError):
                return fail("server error")
            if correct is None:
                return fail("no such account")
            if password != correct:
                return fail("wrong password")
            kv.get(f"avatarID${username}", on_avatar_id)

        def on_avatar_id(avatar_id):
            if isinstance(avatar_id, JobError) or avatar_id is None:
                return fail("server error")
            game = self.game
            if game is not None:
                game.load_entity_anywhere("Avatar", avatar_id)
            # ask the avatar where it is; it answers on_avatar_ready
            # (routed through the dispatcher, queued while it loads)
            self.call_entity(avatar_id, "query_ready", self.id)

        kv.get(f"password${username}", on_password)

    @rpc()
    def on_avatar_ready(self, avatar_id):
        """Avatar answered: it is loaded on this or another game.
        give_client_to handles both: local fast path, or the cross-game
        MT_GIVE_CLIENT_TO handoff (the gate switches its owner entity when
        the avatar's is_player create arrives; the account entity then sees
        on_client_disconnected and cleans itself up)."""
        self.logining = False
        self.give_client_to(avatar_id)

    def on_client_disconnected(self):
        self.destroy()


class Avatar(Entity):
    persistent = True
    persistent_attrs = frozenset({"name", "room"})
    client_attrs = frozenset({"name", "room"})

    def on_created(self):
        self.attrs.set_default("name", "noname")
        self.attrs.set_default("room", "lobby")

    @rpc()
    def query_ready(self, account_id):
        self.call_entity(account_id, "on_avatar_ready", self.id)

    def on_client_connected(self):
        # joining the room = setting the gate-side filter prop
        self.set_filter_prop("room", self.attrs.get("room"))
        self.call_client("show_info", f"welcome {self.attrs.get('name')}")

    @rpc(expose=OWN_CLIENT)
    def enter_room(self, room):
        self.attrs.set("room", room)
        self.set_filter_prop("room", room)
        self.call_client("show_info", f"joined {room}")

    @rpc(expose=OWN_CLIENT)
    def say(self, text):
        room = self.attrs.get("room")
        self.call_filtered_clients(
            "room", FILTER_OP_EQ, room, "hear",
            self.attrs.get("name"), text,
        )


def setup(game):
    game.register_entity_type(Account)
    game.register_entity_type(Avatar)
